"""`accelerate-tpu plan` — the sharding-strategy planner as a command.

Searches the tensor-parallel decode layout for a named in-tree model (the
cost-model planner behind ``sharding_rules="auto"``, `parallel/planner.py`)
and prints the chosen plan: per-leaf PartitionSpecs, the emitted
``(pattern, spec)`` rules table, predicted per-chip HBM bytes and predicted
collective traffic per dispatch — plus the same cost model priced over the
family's hand-written table, so the auto-vs-hand comparison is one command.

Planning is pure shape arithmetic: parameter shapes come from
``jax.eval_shape`` over the module's init where the family allows it (no
weight materialization — planning a 70B layout works on a laptop), and the
mesh is abstract (``--tp 64`` needs no devices). Only ``--refine-top-k``
compiles anything: the top-k candidates' params are placed for real and a
one-token forward is timed per candidate (cost model proposes, hardware
disposes), which requires the tp to fit the visible devices."""

import argparse
import json


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "plan", help="Search + print a sharding plan for a named model"
    )
    parser.add_argument(
        "model", nargs="?", default="llama-tiny",
        help="Named in-tree model (accelerate_tpu.models registry)",
    )
    parser.add_argument("--tp", type=int, default=2, help="Tensor-parallel degree to plan for")
    parser.add_argument("--num-slots", type=int, default=8, help="Serving slots (decode batch rows)")
    parser.add_argument("--max-length", type=int, default=None, help="Per-slot cache length (default: model max)")
    parser.add_argument("--page-size", type=int, default=16, help="KV pool page size (paged cache)")
    parser.add_argument("--no-paged", action="store_true", help="Price the contiguous per-slot KV layout")
    parser.add_argument("--kv-cache-dtype", default="bf16", choices=["bf16", "int8", "fp8_e4m3"],
                        help="KV pool storage dtype the cost model prices")
    parser.add_argument("--weight-dtype", default="bf16", choices=["bf16", "int8"],
                        help="Weight storage dtype (int8 prices quantized kernels + scales)")
    parser.add_argument("--chip", default=None, help="Chip constants (parallel.planner.CHIPS key); default: by backend")
    parser.add_argument("--beam-width", type=int, default=8, help="Beam width for the strategy search")
    parser.add_argument("--refine-top-k", type=int, default=0,
                        help="Compile + time the top-k candidates and pick the measured best "
                        "(needs the plan's mesh to fit the visible devices). Serving plans "
                        "time a one-token forward; --mesh training plans time a fused "
                        "train step (grads + optimizer update included)")
    parser.add_argument("--seq-len", type=int, default=8, help="Init sequence length for shape derivation")
    parser.add_argument("--json", action="store_true", help="Machine-readable plan JSON")
    parser.add_argument(
        "--mesh", default=None,
        help='Training mesh, e.g. "data=4,model=2": switches to the training '
        "planner — params, grads AND optimizer state (ZeRO weight-update "
        "sharding along the data axis) are enumerated and priced together. "
        'Add a pipeline axis ("data=2,model=2,pipeline=2") for the 3D MPMD '
        "planner: byte-balanced (possibly non-uniform) stages, one 2D plan "
        "per stage submesh, and the 1F1B pipeline-bubble term in the "
        "predicted step time",
    )
    parser.add_argument("--batch", type=int, default=8, help="Global batch size (training planner)")
    parser.add_argument("--opt-bytes-per-param", type=float, default=8.0,
                        help="Optimizer bytes/param the cost model prices (fp32 Adam moments: 8)")
    parser.add_argument(
        "--live", action="store_true",
        help="Build --mesh on the visible devices, place all three trees "
        "(params / grads / optimizer state) per plan, and report predicted-vs-live "
        "per-chip bytes off the LIVE shardings (tree_device_nbytes)",
    )
    parser.set_defaults(func=plan_command)
    return parser


#: Families whose modules init from a bare [1, seq] int32 token batch — these
#: plan from `jax.eval_shape` (no weight materialization). Others fall back to
#: building the real bundle.
_CAUSAL_FAMILIES = ("llama", "gpt_neox", "gptj", "opt", "mixtral")


def _model_shapes(name: str, seq_len: int, materialize: bool):
    """(params-or-shapes tree, config, hand rules table, apply_fn-or-None,
    real-params-or-None) for a registry name."""
    import jax
    import jax.numpy as jnp

    from .. import models as model_zoo
    from ..models import CREATE_BY_FAMILY, get_model_family

    family, config = get_model_family(name)
    if materialize or family not in _CAUSAL_FAMILIES:
        bundle = CREATE_BY_FAMILY[family](config, seq_len=seq_len)
        return bundle.params, config, list(bundle.sharding_rules or []), bundle.apply_fn, bundle.params

    module_cls = {
        "llama": model_zoo.LlamaForCausalLM,
        "gpt_neox": model_zoo.GPTNeoXForCausalLM,
        "gptj": model_zoo.GPTJForCausalLM,
        "opt": model_zoo.OPTForCausalLM,
        "mixtral": model_zoo.MixtralForCausalLM,
    }[family]
    module = module_cls(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), jnp.int32)
    shapes = jax.eval_shape(module.init, jax.random.key(0), sample)
    import importlib

    family_module = importlib.import_module(f"accelerate_tpu.models.{family}")
    rules = list(getattr(family_module, f"{family.upper()}_SHARDING_RULES", None) or [])
    return shapes, config, rules, module.apply, None


def _parse_mesh(spec: str):
    """Parse ``"data=4,model=2"`` into an ordered ``{axis: size}`` dict. A bare
    axis name (no ``=``) takes the remaining visible-device count (one only)."""
    axes = {}
    fill = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, size = part.partition("=")
            axes[name.strip()] = int(size)
        else:
            if fill is not None:
                raise SystemExit(f"--mesh {spec!r}: at most one axis may omit its size")
            axes[part] = -1
            fill = part
    if fill is not None:
        import jax

        explicit = 1
        for name, size in axes.items():
            if size > 0:
                explicit *= size
        n = len(jax.devices())
        if n % explicit != 0:
            raise SystemExit(
                f"--mesh {spec!r}: {n} devices not divisible by explicit sizes ({explicit})"
            )
        axes[fill] = n // explicit
    return axes


def _train_plan_command(args, chip):
    """The ``--mesh`` branch: training planner over params+grads+opt state —
    2D ("data", "model"), or the 3D MPMD pipeline planner when the mesh
    carries a "pipeline" axis — optionally measured (``--refine-top-k``) or
    compared against LIVE placements (``--live``)."""
    from ..parallel.planner import (
        measure_train_step,
        plan_train_sharding,
        refine_plans,
        score_rules,
    )

    mesh_axes = _parse_mesh(args.mesh)
    pipelined = int(mesh_axes.get("pipeline", 1)) > 1
    refine = max(0, int(args.refine_top_k))
    if pipelined and refine:
        raise SystemExit(
            "--refine-top-k times single-mesh training plans; an MPMD pipeline "
            "plan's measured step time comes from "
            "`accelerate-tpu bench --mode train --pipeline-ab`"
        )
    params, config, hand_rules, apply_fn, real_params = _model_shapes(
        args.model, args.seq_len, materialize=args.live or refine >= 1
    )
    layered = layered_split = None
    if pipelined:
        # The pipeline planner balances *per-layer* byte weights, so it needs
        # the LayeredApply split. split() is pure pytree indexing — it works
        # on the eval_shape tree, so deviceless 3D planning stays deviceless.
        from ..models import get_model_family, layered_for_family

        family, _ = get_model_family(args.model)
        layered = layered_for_family(family, config)
        layered_split = layered.split(params)
    plan = plan_train_sharding(
        params,
        mesh_axes,
        batch=args.batch,
        seq=args.seq_len,
        opt_bytes_per_param=args.opt_bytes_per_param,
        weight_dtype=args.weight_dtype,
        chip=chip,
        beam_width=args.beam_width,
        layered_split=layered_split,
        top_k=max(refine, 1),
    )
    measurements = None
    if refine >= 1:
        # Measured selection: place each candidate's three trees on the live
        # mesh and time a fused train step (value_and_grad + optimizer update)
        # — the training twin of the serving path's one-token forward.
        plans = plan if isinstance(plan, list) else [plan]
        live_mesh = _build_live_mesh(mesh_axes)
        plan, measured = refine_plans(
            plans,
            lambda p: measure_train_step(
                apply_fn, real_params, live_mesh, p.rules,
                opt_rules=p.opt_rules, batch=args.batch, seq=args.seq_len,
            ),
        )
        measurements = [seconds for _, seconds in measured]
    # The hand-written family tables are single-mesh: there is nothing to
    # score them against on a pipeline mesh (that gap is the point).
    hand = (
        score_rules(
            params, mesh_axes, hand_rules,
            chip=chip, workload=plan.workload, weight_dtype=args.weight_dtype,
        )
        if hand_rules and not pipelined
        else None
    )
    if args.live:
        live = (
            _live_mpmd_bytes(plan, mesh_axes, real_params, layered)
            if pipelined
            else _live_train_bytes(plan, mesh_axes, real_params)
        )
    else:
        live = None

    if args.json:
        payload = {"model": args.model, "mesh": mesh_axes, "plan": plan.to_json()}
        if hand is not None:
            payload["hand_rules"] = {
                "rules": [[p, list(s)] for p, s in hand.rules],
                "predicted": hand.to_json()["predicted"],
                "modeled_cost": hand.cost.total,
            }
            payload["plan"]["modeled_cost"] = plan.cost.total
            payload["auto_beats_hand"] = plan.cost.total <= hand.cost.total
        if measurements is not None:
            payload["refine_measurements_s"] = measurements
        if live is not None:
            payload["live"] = live
        print(json.dumps(payload, indent=2))
        return payload

    print(f"[plan] {args.model} | mesh={mesh_axes} | batch={args.batch} | "
          f"training (opt {args.opt_bytes_per_param} B/param) weights={args.weight_dtype}")
    print()
    print(plan.describe())
    if measurements is not None:
        print()
        print(f"measure-and-refine (top-{len(measurements)}, fused train step):")
        for i, seconds in enumerate(measurements):
            print(f"  candidate {i}: {seconds * 1e6:.1f} us")
    if hand is not None:
        print()
        verdict = "matches or beats" if plan.cost.total <= hand.cost.total else "LOSES TO"
        print(
            f"hand-written family table: modeled cost {hand.cost.total:.3e} "
            f"(per-chip {int(hand.cost.per_chip_total_bytes)} bytes, "
            f"ici {int(hand.cost.collective_bytes)} B/dispatch) — "
            f"auto plan ({plan.cost.total:.3e}) {verdict} it"
        )
    if live is not None:
        print()
        print("predicted vs live per-chip bytes (tree_device_nbytes, device 0):")
        for tree in ("params", "grads", "opt_state"):
            row = live[tree]
            print(
                f"  {tree:<10} predicted {row['predicted_bytes']:>12}  "
                f"live {row['live_bytes']:>12}  error {row['error_pct']:.2f}%"
            )
    return plan


def _build_live_mesh(mesh_axes):
    """A real `Mesh` shaped like the ``--mesh`` axes dict on the visible
    devices (SystemExit when the host is too small for the product)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    sizes = [int(s) for s in mesh_axes.values()]
    n_devices = int(np.prod(sizes))
    devices = jax.devices()
    if len(devices) < n_devices:
        raise SystemExit(
            f"this step needs {n_devices} devices for mesh {dict(mesh_axes)}, "
            f"have {len(devices)}"
        )
    return Mesh(np.array(devices[:n_devices]).reshape(sizes), tuple(mesh_axes))


def _bytes_row(predicted, live):
    predicted, live = float(predicted), float(live)
    err = abs(predicted - live) / live * 100.0 if live else 0.0
    return {
        "predicted_bytes": int(predicted),
        "live_bytes": int(live),
        "error_pct": err,
    }


def _live_train_bytes(plan, mesh_axes, real_params):
    """Place params, a zeros grads tree, and a freshly-initialized Adam state on
    the real devices per the plan (the same derivation seams `prepare()` uses)
    and measure per-chip bytes off the LIVE shardings."""
    import jax
    import optax

    from ..parallel.sharding import (
        derive_opt_state_shardings,
        derive_tp_param_shardings,
        place_params,
        tree_device_nbytes,
    )

    mesh = _build_live_mesh(mesh_axes)
    dev0 = mesh.devices.flat[0]

    param_shardings = derive_tp_param_shardings(real_params, mesh, plan.rules)
    placed = place_params(real_params, param_shardings)
    grads = place_params(jax.tree_util.tree_map(lambda x: jax.numpy.zeros_like(x), real_params), param_shardings)
    tx = optax.adam(1e-3)
    state_shapes = jax.eval_shape(tx.init, placed)
    opt_shardings = derive_opt_state_shardings(
        state_shapes, mesh, None, plan.rules, opt_rules=plan.opt_rules
    )
    opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(placed)

    return {
        "params": _bytes_row(plan.cost.per_chip_param_bytes, tree_device_nbytes(placed, dev0)),
        # Grads carry the parameter dtype and placement, so the param account
        # predicts them too.
        "grads": _bytes_row(plan.cost.per_chip_param_bytes, tree_device_nbytes(grads, dev0)),
        "opt_state": _bytes_row(plan.cost.per_chip_opt_bytes, tree_device_nbytes(opt_state, dev0)),
    }


def _init_placed_opt_state(tx, placed, opt_shardings):
    """Initialize one stage's Adam state pinned to its derived shardings —
    a helper so each stage's jit is a distinct function object compiled once,
    not a fresh cache built inside the stage loop."""
    import jax

    return jax.jit(tx.init, out_shardings=opt_shardings)(placed)


def _live_mpmd_bytes(plan, mesh_axes, real_params, layered):
    """The ``--live`` account for an MPMD pipeline plan: place every stage's
    params + grads accumulator + Adam state on its OWN pipeline submesh per the
    stage's rules tables (the same derivations `parallel.mpmd` runs) and
    compare the busiest stage's per-chip bytes against the plan's prediction —
    the plan prices exactly the busiest stage, because that chip's HBM is the
    binding constraint."""
    import jax
    import optax

    from ..parallel.mesh import slice_mesh
    from ..parallel.planner import build_stage_tree
    from ..parallel.sharding import (
        derive_opt_state_shardings,
        derive_tp_param_shardings,
        place_params,
        tree_device_nbytes,
    )

    mesh = _build_live_mesh(mesh_axes)
    submeshes = slice_mesh(mesh, "pipeline")
    prelude, layers, tail = layered.split(real_params)
    tx = optax.adam(1e-3)

    param_live, grad_live, opt_live = [], [], []
    for k, submesh in enumerate(submeshes):
        tree = build_stage_tree(prelude, layers, tail, plan.stage_plan, k)
        shardings = derive_tp_param_shardings(tree, submesh, list(plan.stage_rules(k)))
        placed = place_params(tree, shardings)
        grads = place_params(
            jax.tree_util.tree_map(lambda x: jax.numpy.zeros_like(x), tree), shardings
        )
        state_shapes = jax.eval_shape(tx.init, placed)
        opt_shardings = derive_opt_state_shardings(
            state_shapes, submesh, None, list(plan.stage_rules(k)),
            opt_rules=list(plan.stage_opt_rules(k) or []) or None,
        )
        opt_state = _init_placed_opt_state(tx, placed, opt_shardings)
        dev = submesh.devices.flat[0]
        param_live.append(tree_device_nbytes(placed, dev))
        grad_live.append(tree_device_nbytes(grads, dev))
        opt_live.append(tree_device_nbytes(opt_state, dev))

    out = {
        "params": _bytes_row(plan.cost.per_chip_param_bytes, max(param_live)),
        "grads": _bytes_row(plan.cost.per_chip_param_bytes, max(grad_live)),
        "opt_state": _bytes_row(plan.cost.per_chip_opt_bytes, max(opt_live)),
    }
    out["per_stage_param_bytes"] = [int(b) for b in param_live]
    return out


def plan_command(args):
    import numpy as np

    from ..parallel.planner import (
        CHIPS,
        measure_forward_step,
        plan_serving_sharding,
        refine_plans,
        score_rules,
    )

    chip = CHIPS[args.chip] if args.chip else None
    if args.mesh:
        return _train_plan_command(args, chip)
    refine = max(0, int(args.refine_top_k))
    params, config, hand_rules, apply_fn, real_params = _model_shapes(
        args.model, args.seq_len, materialize=refine >= 1
    )
    max_length = int(args.max_length or config.max_position_embeddings)
    paged = not args.no_paged
    if paged:
        pages_per_slot = -(-max_length // args.page_size)
        padded_length = pages_per_slot * args.page_size
        num_pages = args.num_slots * pages_per_slot + 1
    else:
        padded_length = max_length
        num_pages = 0

    mesh = {"model": int(args.tp)}
    plan_kwargs = dict(
        num_slots=args.num_slots,
        padded_length=padded_length,
        paged=paged,
        page_size=args.page_size,
        num_pages=num_pages,
        kv_cache_dtype=args.kv_cache_dtype,
        weight_dtype=args.weight_dtype,
        chip=chip,
        beam_width=args.beam_width,
    )
    measurements = None
    if refine >= 1:
        # Measured selection needs real devices: build the live submesh and
        # time a one-token forward per candidate (refine-top-k 1 still
        # measures the single chosen plan).
        from ..parallel.sharding import serving_tp_mesh

        live_mesh = serving_tp_mesh(args.tp)
        plans = plan_serving_sharding(params, live_mesh, config, top_k=refine, **plan_kwargs)
        if not isinstance(plans, list):
            plans = [plans]
        plan, measured = refine_plans(
            plans,
            lambda p: measure_forward_step(
                apply_fn, real_params, live_mesh, p.rules, batch=1
            ),
        )
        measurements = [(i, seconds) for i, (_, seconds) in enumerate(measured)]
    else:
        plan = plan_serving_sharding(params, mesh, config, **plan_kwargs)

    hand = (
        score_rules(
            params, mesh, hand_rules,
            chip=chip, workload=plan.workload, weight_dtype=args.weight_dtype,
        )
        if hand_rules
        else None
    )

    if args.json:
        payload = {"model": args.model, "plan": plan.to_json()}
        if hand is not None:
            payload["hand_rules"] = {
                "rules": [[p, list(s)] for p, s in hand.rules],
                "predicted": hand.to_json()["predicted"],
                "modeled_cost": hand.cost.total,
            }
            payload["plan"]["modeled_cost"] = plan.cost.total
            payload["auto_beats_hand"] = plan.cost.total <= hand.cost.total
        if measurements is not None:
            payload["refine_measurements_s"] = [s for _, s in measurements]
        print(json.dumps(payload, indent=2))
        return payload

    print(f"[plan] {args.model} | tp={args.tp} | slots={args.num_slots} | "
          f"{'paged' if paged else 'contiguous'} kv={args.kv_cache_dtype} "
          f"weights={args.weight_dtype}")
    print()
    print(plan.describe())
    if measurements is not None:
        print()
        print("measure-and-refine (top-{}):".format(len(measurements)))
        for i, seconds in measurements:
            print(f"  candidate {i}: {seconds * 1e6:.1f} us")
    if hand is not None:
        print()
        verdict = "matches or beats" if plan.cost.total <= hand.cost.total else "LOSES TO"
        print(
            f"hand-written family table: modeled cost {hand.cost.total:.3e} "
            f"(per-chip {int(hand.cost.per_chip_total_bytes)} bytes, "
            f"ici {int(hand.cost.collective_bytes)} B/dispatch) — "
            f"auto plan ({plan.cost.total:.3e}) {verdict} it"
        )
    return plan

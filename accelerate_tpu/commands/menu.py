"""Interactive selection widget for `accelerate-tpu config` (parity: reference
`commands/menu/` — a ~450 LoC arrow-key cursor menu; here one module).

`select(prompt, options)` renders an arrow-key menu on a real terminal (raw-mode
reads, no curses dependency) and degrades to a numbered prompt when stdin is not a
TTY — which is also what makes the questionnaire scriptable in tests.
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

_UP = ("\x1b[A", "k")
_DOWN = ("\x1b[B", "j")
_ENTER = ("\r", "\n")
_INTERRUPT = ("\x03", "\x04", "\x1b")


def _read_key() -> str:
    import os
    import select
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setraw(fd)
        # os.read, NOT sys.stdin.read: the TextIOWrapper would slurp the whole
        # escape burst into its own buffer, making the select() peek below always
        # see an empty fd (every arrow would then look like a bare ESC).
        ch = os.read(fd, 1).decode(errors="replace")
        if ch == "\x1b":
            # Arrow keys arrive as a 3-byte burst; a bare ESC press arrives alone.
            # Peek instead of blocking so ESC can mean "cancel". os.read may
            # short-read when the burst splits across packets (slow links), so
            # keep reading until both continuation bytes arrive or the peek dries.
            while len(ch) < 3 and select.select([fd], [], [], 0.05)[0]:
                ch += os.read(fd, 3 - len(ch)).decode(errors="replace")
            if ch != "\x1b" and len(ch) < 3:
                ch = ""  # truncated burst: drop rather than misparse
        return ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def _render(options: Sequence[str], cursor: int, first: bool):
    if not first:
        sys.stdout.write(f"\x1b[{len(options)}A")  # move back up over the menu
    for i, opt in enumerate(options):
        marker = "➤" if i == cursor else " "
        line = f" {marker} {opt}"
        sys.stdout.write("\x1b[2K" + line + "\n")
    sys.stdout.flush()


def _arrow_menu(prompt: str, options: Sequence[str], default: int) -> int:
    print(prompt + " (arrows + enter)")
    cursor = default
    first = True
    while True:
        _render(options, cursor, first)
        first = False
        key = _read_key()
        if key in _UP:
            cursor = (cursor - 1) % len(options)
        elif key in _DOWN:
            cursor = (cursor + 1) % len(options)
        elif key in _ENTER:
            return cursor
        elif key in _INTERRUPT:
            raise KeyboardInterrupt
        elif key.isdigit() and int(key) < len(options):
            return int(key)


def _numbered_menu(prompt: str, options: Sequence[str], default: int) -> int:
    print(prompt)
    for i, opt in enumerate(options):
        print(f"  [{i}] {opt}")
    while True:
        raw = input(f"Selection [{default}]: ").strip()
        if not raw:
            return default
        try:
            idx = int(raw)
        except ValueError:
            print(f"Please enter a number 0..{len(options) - 1}")
            continue
        if 0 <= idx < len(options):
            return idx
        print(f"Please enter a number 0..{len(options) - 1}")


def select(prompt: str, options: Sequence[str], default: int = 0) -> int:
    """Return the index of the chosen option."""
    interactive = sys.stdin.isatty() and sys.stdout.isatty()
    if interactive:
        try:
            return _arrow_menu(prompt, options, default)
        except (ImportError, OSError):
            pass  # no termios (or odd terminal): fall through to numbered prompt
    return _numbered_menu(prompt, options, default)


def select_value(prompt: str, options: Sequence[str], default: Optional[str] = None) -> str:
    """Like `select`, returning the option string itself."""
    idx = options.index(default) if default in options else 0
    return options[select(prompt, options, idx)]

"""`accelerate-tpu env` — print the environment (parity: reference commands/env.py:47)."""

import argparse
import os
import platform


def register_subcommand(subparsers):
    parser = subparsers.add_parser("env", help="Print environment information")
    parser.add_argument("--config_file", default=None, help="Config file to inspect")
    parser.set_defaults(func=env_command)
    return parser


def env_command(args):
    import jax

    import accelerate_tpu

    info = {
        "`accelerate_tpu` version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "JAX version": jax.__version__,
        "JAX backend": jax.default_backend(),
        "Device count (global/local)": f"{jax.device_count()}/{jax.local_device_count()}",
        "Device kind": jax.devices()[0].device_kind,
        "Process count": jax.process_count(),
    }
    try:
        import flax

        info["Flax version"] = flax.__version__
    except ImportError:
        pass
    try:
        import optax

        info["Optax version"] = optax.__version__
    except ImportError:
        pass
    accelerate_env = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_TPU_")}
    print("\nCopy-and-paste the text below in your GitHub issue\n")
    print("\n".join([f"- {prop}: {val}" for prop, val in info.items()]))
    if accelerate_env:
        print("- Environment config:")
        print("\n".join([f"  - {k}={v}" for k, v in sorted(accelerate_env.items())]))
    config_file = args.config_file or default_config_file()
    if os.path.isfile(config_file):
        with open(config_file) as f:
            print(f"- Config file ({config_file}):\n" + "".join(f"  {line}" for line in f))
    return info


def default_config_file() -> str:
    cache_dir = os.environ.get(
        "ACCELERATE_TPU_CONFIG_HOME", os.path.join(os.path.expanduser("~"), ".cache", "accelerate_tpu")
    )
    return os.path.join(cache_dir, "default_config.yaml")

"""`accelerate-tpu analyze` — static TPU-hazard lint over Python trees.

Scans the given files/directories with the `analysis` linter (pure stdlib
``ast`` — no backend is ever initialized, so this runs offline on CPU-only
lint boxes) and reports findings as compiler-style text or ``--json``.

Exit codes (the CI contract):
  0 — no findings at or above the ``--fail-on`` threshold
  1 — at least one finding at/above the threshold
  2 — usage error (bad path, bad threshold)

`--fail-on error` (the default) gates only on discipline breaks; `--fail-on
warn` additionally fails on recompile/throughput hazards.
"""

from __future__ import annotations

import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "analyze",
        help="Statically lint Python sources for TPU hazards (host syncs, recompile triggers)",
        description=__doc__,
    )
    parser.add_argument(
        "paths",
        nargs="+",
        help="Files or directories to scan (directories are walked for *.py)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="Emit a machine-readable JSON report on stdout",
    )
    parser.add_argument(
        "--fail-on",
        default="error",
        choices=("warn", "error"),
        help="Exit 1 when any finding at/above this severity exists (default: error)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="Print the rule catalog (id, slug, severity, summary) and exit",
    )
    parser.set_defaults(func=analyze_command)
    return parser


def analyze_command(args):
    # The static half only — never import the trace-guard (and with it jax's
    # runtime machinery) on the lint path.
    from ..analysis.report import count_by_severity, render_json, render_text
    from ..analysis.rules import RULES, severity_at_least
    from ..analysis.runner import analyze_paths

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.id}  {rule.slug:<24} {rule.severity:<5} {rule.summary}")
        raise SystemExit(0)

    try:
        findings, scanned = analyze_paths(args.paths)
    except FileNotFoundError as exc:
        print(f"accelerate-tpu analyze: {exc}", file=sys.stderr)
        raise SystemExit(2)

    if args.as_json:
        print(render_json(findings, scanned))
    else:
        print(render_text(findings, scanned))

    failing = [f for f in findings if severity_at_least(f.severity, args.fail_on)]
    raise SystemExit(1 if failing else 0)

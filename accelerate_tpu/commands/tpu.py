"""`accelerate-tpu tpu-config` + the pod launcher (parity: reference commands/tpu.py:90-150
and tpu_pod_launcher commands/launch.py:821).

Both work by re-running a command on every worker of a Cloud TPU pod slice over
`gcloud compute tpus tpu-vm ssh --worker all`. `--dry_run` prints the command instead of
executing (used by the CLI tests; no gcloud in CI)."""

import argparse
import os
import subprocess
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser("tpu-config", help="Run setup commands on every pod worker")
    parser.add_argument("--tpu_name", required=False, default=None)
    parser.add_argument("--tpu_zone", required=False, default=None)
    parser.add_argument("--command", action="append", default=None, help="Command(s) to run on each worker")
    parser.add_argument("--command_file", default=None, help="File with one command per line")
    parser.add_argument("--install_accelerate", action="store_true", help="Install accelerate-tpu on workers first")
    parser.add_argument("--accelerate_version", default="latest")
    parser.add_argument("--debug", "--dry_run", dest="dry_run", action="store_true", help="Print, don't run")
    parser.set_defaults(func=tpu_command_launcher)
    return parser


def build_ssh_command(tpu_name: str, tpu_zone: str, remote_command: str) -> list:
    return [
        "gcloud",
        "compute",
        "tpus",
        "tpu-vm",
        "ssh",
        tpu_name,
        "--zone",
        tpu_zone,
        "--command",
        remote_command,
        "--worker",
        "all",
    ]


def tpu_command_launcher(args):
    commands = list(args.command or [])
    if args.command_file:
        with open(args.command_file) as f:
            commands.extend(line.strip() for line in f if line.strip())
    if args.install_accelerate:
        version = "" if args.accelerate_version == "latest" else f"=={args.accelerate_version}"
        commands.insert(0, f"pip install accelerate-tpu{version}")
    if not commands:
        raise ValueError("No commands given: pass --command or --command_file")
    if not args.tpu_name or not args.tpu_zone:
        raise ValueError("--tpu_name and --tpu_zone are required")
    remote = "; ".join(commands)
    cmd = build_ssh_command(args.tpu_name, args.tpu_zone, remote)
    if args.dry_run:
        print("Running {}".format(" ".join(cmd)))
        return cmd
    print(f"Running {remote} on {args.tpu_name}...")
    subprocess.run(cmd, check=True)
    print("Successfully setup pod.")


def pod_launcher(args, config: dict):
    """Re-launch `accelerate-tpu launch` on every pod worker (reference
    tpu_pod_launcher commands/launch.py:821-878).

    Each worker re-runs the same launch command minus --tpu_use_cluster; JAX's
    coordination service discovers pod topology from TPU metadata, so no explicit
    process ids are needed on Cloud TPU."""
    tpu_name = args.tpu_name or config.get("tpu_name")
    tpu_zone = args.tpu_zone or config.get("tpu_zone")
    if not tpu_name or not tpu_zone:
        raise ValueError("Pod launch needs --tpu_name and --tpu_zone (or config file values)")
    inner = [
        "ACCELERATE_TPU_MULTIHOST=1",
        "python",
        "-m",
        "accelerate_tpu.commands.launch",
        args.training_script,
        *args.training_script_args,
    ]
    remote_command = " ".join(inner)
    cmd = build_ssh_command(tpu_name, tpu_zone, remote_command)
    if getattr(args, "dry_run", False):
        print("Running {}".format(" ".join(cmd)))
        return cmd
    subprocess.run(cmd, check=True)

"""`accelerate-tpu` console entry: subcommand dispatch (parity: reference
commands/accelerate_cli.py:26-46).

Subcommands register themselves via `register_subcommand(parser)`; this module stays a
thin dispatcher.
"""

import argparse


def get_command_parser():
    parser = argparse.ArgumentParser(
        "accelerate-tpu", usage="accelerate-tpu <command> [<args>]", allow_abbrev=False
    )
    subparsers = parser.add_subparsers(help="accelerate-tpu command helpers", dest="command")

    # Subcommand modules are imported lazily so `--help` stays fast and optional deps
    # (yaml, rich) are only touched by the commands that need them.
    from . import analysis, chaos, config, convert, env, estimate, launch, plan, serve, test, tpu, trace

    analysis.register_subcommand(subparsers)
    chaos.register_subcommand(subparsers)
    config.register_subcommand(subparsers)
    env.register_subcommand(subparsers)
    estimate.register_subcommand(subparsers)
    launch.register_subcommand(subparsers)
    plan.register_subcommand(subparsers)
    serve.register_subcommand(subparsers)
    test.register_subcommand(subparsers)
    tpu.register_subcommand(subparsers)
    trace.register_subcommand(subparsers)
    convert.register_subcommand(subparsers)
    return parser


def main():
    parser = get_command_parser()
    args = parser.parse_args()
    if not hasattr(args, "func"):
        parser.print_help()
        raise SystemExit(1)
    args.func(args)


if __name__ == "__main__":
    main()

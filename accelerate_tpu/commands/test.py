"""`accelerate-tpu test` — sanity-run the bundled end-to-end script (parity: reference
commands/test.py:22-55, which launches test_utils/scripts/test_script.py)."""

import argparse
import os
import subprocess
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser("test", help="Run the end-to-end sanity test")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--cpu", action="store_true", help="Run on the virtual CPU mesh")
    parser.add_argument(
        "--num_processes",
        type=int,
        default=None,
        help="Also run the script across N REAL coordinated processes (debug launcher)",
    )
    parser.set_defaults(func=test_command)
    return parser


def _script_path() -> str:
    import accelerate_tpu.test_utils.scripts as scripts_mod

    return os.path.join(os.path.dirname(scripts_mod.__file__), "test_script.py")


def _script_main():
    """Module-level worker (spawn-picklable) running the bundled everything-script."""
    import runpy

    runpy.run_path(_script_path(), run_name="__main__")


def test_command(args):
    script = _script_path()
    env = os.environ.copy()
    if args.cpu:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    if args.num_processes and args.num_processes > 1:
        # Multi-process leg: the same checks across N REAL coordinated processes
        # (cross-process RNG sync, object plane, trigger visibility — contracts a
        # single process can't falsify).
        if 8 % args.num_processes != 0:
            # The scripts use global batch sizes of 8/16; a non-divisor N would
            # fail with misleading in-script assertions rather than a usage error.
            raise SystemExit(
                f"--num_processes must divide 8 (the test scripts' global batch "
                f"size); got {args.num_processes}. Use 2, 4, or 8."
            )
        from ..launchers import debug_launcher

        print(f"Running the test script across {args.num_processes} coordinated processes...")
        try:
            debug_launcher(_script_main, num_processes=args.num_processes)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            raise SystemExit(1) from e
        print("Multi-process run passed.")
    print("Running:  " + " ".join([sys.executable, script]))
    result = subprocess.run([sys.executable, script], env=env)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    else:
        raise SystemExit(result.returncode)

"""`accelerate-tpu test` — sanity-run the bundled end-to-end script (parity: reference
commands/test.py:22-55, which launches test_utils/scripts/test_script.py)."""

import argparse
import os
import subprocess
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser("test", help="Run the end-to-end sanity test")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--cpu", action="store_true", help="Run on the virtual CPU mesh")
    parser.set_defaults(func=test_command)
    return parser


def test_command(args):
    import accelerate_tpu.test_utils.scripts as scripts_mod

    script = os.path.join(os.path.dirname(scripts_mod.__file__), "test_script.py")
    env = os.environ.copy()
    if args.cpu:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    print("Running:  " + " ".join([sys.executable, script]))
    result = subprocess.run([sys.executable, script], env=env)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    else:
        raise SystemExit(result.returncode)

"""`accelerate-tpu launch` — run a training script with the env-var protocol
(parity: reference commands/launch.py:1068-1091 + utils/launch.py env builders).

The launcher serializes everything into `ACCELERATE_TPU_*` env vars and runs the user
script; `Accelerator()` inside the script reads them back (the same two-sided protocol
as the reference). Dispatch:
  - single host → subprocess with env (reference simple_launcher :690)
  - multi-host pod, this host → env with coordinator vars (reference tpu_launcher :790)
  - `--tpu_use_cluster` → re-launch this command on every pod worker over gcloud ssh
    (reference tpu_pod_launcher :821); see commands/tpu.py.
"""

import argparse
import os
import subprocess
import sys

from .config import load_config_file


def register_subcommand(subparsers):
    parser = subparsers.add_parser("launch", help="Launch a script with accelerate-tpu", add_help=True)
    add_launch_args(parser)
    parser.set_defaults(func=launch_command)
    return parser


def add_launch_args(parser):
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--mixed_precision", default=None, choices=[None, "no", "bf16", "fp16", "fp8"])
    parser.add_argument("--num_processes", type=int, default=None, help="Number of host processes (pod hosts)")
    parser.add_argument("--process_id", type=int, default=None, help="This host's rank (multi-host)")
    parser.add_argument("--coordinator_address", default=None, help="host:port of process 0 (multi-host)")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    parser.add_argument("--debug", action="store_true", help="Enable collective shape verification")
    parser.add_argument("--cpu", action="store_true", help="Force host-CPU platform (debug/testing)")
    parser.add_argument("--num_cpu_devices", type=int, default=None, help="Virtual CPU device count (testing)")
    parser.add_argument(
        "--profile_dir",
        default=None,
        help="Arm on-demand profiling in every worker (telemetry.ProfilerManager): "
        "traces land in this directory; trigger a capture on a live run by "
        "touching <dir>/CAPTURE or sending SIGUSR2 (docs/reference/cli.md)",
    )
    parser.add_argument(
        "--trace_dir",
        default=None,
        help="Arm request-scoped tracing + the crash/hang flight recorder in every "
        "worker (telemetry.tracing): span streams and trace dumps land in this "
        "directory; `accelerate-tpu trace dump --dir DIR` renders them for "
        "Perfetto. A trace id is minted once so supervised restarts stitch into "
        "one timeline (docs/reference/cli.md)",
    )
    for axis in ("data", "fsdp", "model", "seq", "expert", "stage"):
        parser.add_argument(f"--mesh_{axis}", type=int, default=None, help=f"Mesh axis size for `{axis}`")
    parser.add_argument("--max_restarts", type=int, default=0, help="Restart budget on child failure (elastic supervision)")
    parser.add_argument(
        "--grace_period",
        type=float,
        default=None,
        help="Seconds a signaled child gets to checkpoint (default 30, or the config file's value)",
    )
    parser.add_argument(
        "--restart_backoff",
        type=float,
        default=None,
        help="Base seconds of linear restart backoff (default 1, or the config file's value)",
    )
    parser.add_argument(
        "--max_backoff",
        type=float,
        default=None,
        help="Backoff ceiling in seconds so a crash loop with a large budget never sleeps unboundedly (default 30)",
    )
    parser.add_argument(
        "--crash_loop_threshold",
        type=int,
        default=None,
        help="Abort supervision after N consecutive identical-exit-code crashes where the child "
        "lived under the uptime floor (default 3; 0 disables crash-loop detection)",
    )
    parser.add_argument(
        "--fault_plan",
        default=None,
        help="Chaos fault plan (JSON file) exported to every worker as ACCELERATE_TPU_FAULT_PLAN "
        "(accelerate-tpu chaos; docs/fault_tolerance.md) — fault-injection runs only",
    )
    parser.add_argument(
        "--async_save",
        action="store_true",
        help="Asynchronous checkpointing in every worker (ACCELERATE_TPU_ASYNC_SAVE): "
        "save_state blocks only for the device->host snapshot; serialize+fsync+publish "
        "run on a background committer (docs/guides/checkpointing.md)",
    )
    parser.add_argument(
        "--sharded_save",
        action="store_true",
        help="Per-host sharded checkpoints (ACCELERATE_TPU_SHARDED_SAVE): each process "
        "writes only its addressable mesh shards into its own host_*/ subdirectory; "
        "restore gathers on load (docs/guides/checkpointing.md)",
    )
    parser.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="Serving fleet size exported as ACCELERATE_TPU_SERVE_REPLICAS: a serving "
        "script that builds a router.Router(replicas=None) sizes its engine fleet from "
        "the launcher (docs/serving.md Replication)",
    )
    parser.add_argument("--tpu_use_cluster", action="store_true", help="Launch on every worker of a TPU pod")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    from .cloud import add_cloud_args

    add_cloud_args(parser)
    parser.add_argument("training_script", type=str, help="The script to launch")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER, help="Script arguments")
    return parser


def build_launch_env(args, config: dict) -> dict:
    """Merge CLI args over the config file into the env-var protocol (reference
    utils/launch.py:76-148 prepare_simple_launcher_cmd_env)."""
    env = os.environ.copy()

    def pick(cli_val, key, default=None):
        if cli_val is not None:
            return cli_val
        return config.get(key, default)

    mp = pick(args.mixed_precision, "mixed_precision")
    if mp:
        env["ACCELERATE_TPU_MIXED_PRECISION"] = str(mp)
    gas = pick(args.gradient_accumulation_steps, "gradient_accumulation_steps")
    if gas:
        env["ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS"] = str(gas)
    mesh_cfg = config.get("mesh", {}) or {}
    for axis in ("data", "fsdp", "model", "seq", "expert", "stage"):
        val = getattr(args, f"mesh_{axis}")
        if val is None:
            val = mesh_cfg.get(axis)
        if val is not None:
            env[f"ACCELERATE_TPU_MESH_{axis.upper()}"] = str(val)
    if args.debug or config.get("debug"):
        env["ACCELERATE_TPU_DEBUG_MODE"] = "1"
    profile_dir = pick(args.profile_dir, "profile_dir")
    if profile_dir:
        env["ACCELERATE_TPU_PROFILE_DIR"] = str(profile_dir)
    trace_dir = pick(getattr(args, "trace_dir", None), "trace_dir")
    if trace_dir:
        from ..telemetry.tracing import TRACE_DIR_ENV, TRACE_ID_ENV, new_id

        env[TRACE_DIR_ENV] = str(trace_dir)
        # Mint the trace id ONCE at launch (unless an outer launcher already
        # did): every worker and every supervised restart shares it, so the
        # whole job stitches into one Perfetto timeline.
        env.setdefault(TRACE_ID_ENV, new_id())
    fault_plan = pick(getattr(args, "fault_plan", None), "fault_plan")
    if fault_plan:
        env["ACCELERATE_TPU_FAULT_PLAN"] = str(fault_plan)
    if getattr(args, "async_save", False) or config.get("async_save"):
        env["ACCELERATE_TPU_ASYNC_SAVE"] = "1"
    if getattr(args, "sharded_save", False) or config.get("sharded_save"):
        env["ACCELERATE_TPU_SHARDED_SAVE"] = "1"
    replicas = pick(getattr(args, "replicas", None), "replicas")
    if replicas:
        env["ACCELERATE_TPU_SERVE_REPLICAS"] = str(replicas)

    # Plugin blocks from the questionnaire YAML -> the env protocol the worker-side
    # dataclasses' __post_init__ reads (reference utils/launch.py:226-267 FSDP_* block).
    fsdp_cfg = config.get("fsdp_config") or {}
    if fsdp_cfg:
        env["ACCELERATE_TPU_USE_FSDP"] = "1"
        mapping = {
            "sharding_strategy": "SHARDING_STRATEGY",
            "min_num_params": "MIN_NUM_PARAMS",
            "cpu_offload": "OFFLOAD_PARAMS",
            "activation_checkpointing": "ACTIVATION_CHECKPOINTING",
            "state_dict_type": "STATE_DICT_TYPE",
            "auto_wrap_policy": "AUTO_WRAP_POLICY",
            "transformer_cls_names_to_wrap": "TRANSFORMER_CLS_TO_WRAP",
            "param_dtype": "PARAM_DTYPE",
            "reduce_dtype": "REDUCE_DTYPE",
            "sync_module_states": "SYNC_MODULE_STATES",
            "offload_optimizer_device": "OFFLOAD_OPTIMIZER_DEVICE",
            "offload_dir": "OFFLOAD_DIR",
        }
        for key, suffix in mapping.items():
            if key in fsdp_cfg and fsdp_cfg[key] is not None:
                val = fsdp_cfg[key]
                if isinstance(val, bool):
                    val = str(val).lower()
                elif isinstance(val, (list, tuple)):
                    if any("," in str(v) for v in val):
                        raise ValueError(
                            f"fsdp_config.{key} entries cannot contain ',' (the env-protocol "
                            f"separator): {val}. Use a comma-free regex (e.g. 'layer_[0-9]+')."
                        )
                    val = ",".join(str(v) for v in val)
                env[f"ACCELERATE_TPU_FSDP_{suffix}"] = str(val)
    sp_cfg = config.get("sequence_parallel_config") or {}
    if sp_cfg:
        env["ACCELERATE_TPU_SP_MODE"] = str(sp_cfg.get("mode", "ring"))
        if sp_cfg.get("block_size"):
            env["ACCELERATE_TPU_SP_BLOCK_SIZE"] = str(sp_cfg["block_size"])
    if config.get("compilation_cache"):
        env["ACCELERATE_TPU_COMPILATION_CACHE"] = str(config["compilation_cache"])
    if config.get("downcast_bf16"):
        env["ACCELERATE_TPU_DOWNCAST_BF16"] = "true"

    num_processes = pick(args.num_processes, "num_processes", 1)
    coordinator = pick(args.coordinator_address, "coordinator_address")
    if num_processes and int(num_processes) > 1:
        if coordinator is None:
            raise ValueError("--coordinator_address is required when --num_processes > 1")
        process_id = args.process_id
        if process_id is None:
            process_id = int(os.environ.get("ACCELERATE_TPU_PROCESS_ID", "0"))
        env["ACCELERATE_TPU_COORDINATOR_ADDRESS"] = str(coordinator)
        env["ACCELERATE_TPU_NUM_PROCESSES"] = str(num_processes)
        env["ACCELERATE_TPU_PROCESS_ID"] = str(process_id)
    if args.cpu or args.num_cpu_devices:
        from ..utils.environment import set_host_device_count_flag

        env["JAX_PLATFORMS"] = "cpu"
        # Only an EXPLICIT --num_cpu_devices overrides an inherited count; bare
        # --cpu keeps whatever the environment already chose.
        env["XLA_FLAGS"] = set_host_device_count_flag(
            env.get("XLA_FLAGS", ""), args.num_cpu_devices or 8, override=bool(args.num_cpu_devices)
        )
    return env


def launch_command(args):
    config = load_config_file(args.config_file)
    if args.cloud or config.get("compute_environment") == "GCP_CLOUD":
        from .cloud import cloud_launcher

        return cloud_launcher(args, config)
    if args.tpu_use_cluster or config.get("tpu_use_cluster"):
        from .tpu import pod_launcher

        return pod_launcher(args, config)
    env = build_launch_env(args, config)
    cmd = [sys.executable, args.training_script] + list(args.training_script_args)
    max_restarts = args.max_restarts or int(config.get("max_restarts", 0) or 0)
    if max_restarts > 0:
        from ..fault_tolerance import Supervisor

        grace = args.grace_period if args.grace_period is not None else float(config.get("grace_period", 30.0))
        backoff = args.restart_backoff if args.restart_backoff is not None else float(config.get("restart_backoff", 1.0))
        max_backoff = args.max_backoff if args.max_backoff is not None else float(config.get("max_backoff", 30.0))
        crash_loop = (
            args.crash_loop_threshold
            if args.crash_loop_threshold is not None
            else int(config.get("crash_loop_threshold", 3))
        )
        tracer = None
        if env.get("ACCELERATE_TPU_TRACE_DIR"):
            # Supervisor-side tracing: attempt spans + per-attempt parent ids
            # injected into each child, so the restart chain stitches.
            from ..telemetry import FlightRecorder
            from ..telemetry.tracing import Tracer

            tracer = Tracer(
                recorder=FlightRecorder(log_dir=env["ACCELERATE_TPU_TRACE_DIR"]),
                trace_id=env.get("ACCELERATE_TPU_TRACE_ID"),
                category="supervisor",
            )
        code = Supervisor(
            cmd,
            env=env,
            max_restarts=max_restarts,
            grace_period=grace,
            backoff_seconds=backoff,
            max_backoff_seconds=max_backoff,
            crash_loop_threshold=crash_loop,
            tracer=tracer,
        ).run()
        if code != 0:
            raise SystemExit(code)
        return
    process = subprocess.run(cmd, env=env)
    if process.returncode != 0:
        raise SystemExit(process.returncode)


def main():
    parser = argparse.ArgumentParser("accelerate-tpu-launch", allow_abbrev=False)
    add_launch_args(parser)
    args = parser.parse_args()
    launch_command(args)


if __name__ == "__main__":
    main()

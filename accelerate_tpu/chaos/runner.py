"""ChaosRunner: drive real train/serve workloads under a fault plan and emit a
machine-readable invariant report.

The runner owns the *invariants* the stack promises under faults, checked
against evidence the workload journals as it runs:

  - **resume_exactness** — every restart resumes from the last *committed*
    checkpoint: the resolved manifest's step matches the newest
    independently-verified checkpoint, and the restored parameter digest
    matches what the journal recorded when that step was committed.
  - **no_torn_resolved** — `resolve("latest")` never hands a resume a
    checkpoint whose digests fail. Verification here is INDEPENDENT of
    `checkpointing.verify_checkpoint_dir` (the runner re-hashes files
    itself), so a regression — or the `harness.disable_verification`
    seeded-regression fixture — turns the report red instead of being
    vacuously green.
  - **restart_budget** — restarts and injected downtime stay inside budget,
    and the run actually completes.
  - **terminal_finish_reasons** — under serving faults, every accepted request
    drains to a terminal `finish_reason`; the engine recovers after a
    dispatch failure; the bounded queue never exceeds its cap.
  - **ledger_reconciles** — `chaos_injected_total{kind=...}` counters match
    the injection journal, and injected downtime shows up in the goodput
    ledger (slow fsyncs inside `save_state` land in the "checkpoint" cause,
    resumes in "restart").

Workloads are deliberately tiny (the regression model / a tiny llama) so full
sweeps — SIGKILL at every boundary, torn bytes at every offset — run on CPU in
tier-1 time. `run_supervised_train` additionally drives the real
`fault_tolerance.Supervisor` over a subprocess workload with the plan
propagated via ``ACCELERATE_TPU_FAULT_PLAN`` (`chaos.workload`).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..logging import get_logger
from ..telemetry import MetricsRegistry
from ..telemetry.flight_recorder import FlightRecorder, collect_trace_dir
from ..telemetry.tracing import Tracer
from .injectors import (
    ChaosSession,
    FilesystemInjector,
    HarnessInjector,
    InjectedKill,
    RouterInjector,
    ServingInjector,
    StepBoundaryInjector,
)
from .plan import FAULT_PLAN_ENV, FaultPlan

logger = get_logger(__name__)


class _GracefulPreemption(Exception):
    """In-process stand-in for the SIGTERM -> checkpoint -> exit-143 handoff."""


def _reason_counts(finish_reasons: Dict[int, Optional[str]]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for reason in finish_reasons.values():
        key = reason if reason is not None else "none"
        out[key] = out.get(key, 0) + 1
    return out


# ------------------------------------------------------------------ independent evidence
def independent_verify(directory: str) -> bool:
    """Re-hash every file a checkpoint's MANIFEST.json names, with our own
    hashlib walk — NOT `checkpointing.verify_checkpoint_dir`, which a chaos
    plan (or a real regression) may have neutered. The auditor must never
    share machinery with the system it audits."""
    manifest_path = os.path.join(str(directory), "MANIFEST.json")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):  # ValueError covers JSON errors AND flipped-byte utf-8 tears
        return False
    for rel, digest in manifest.get("files", {}).items():
        h = hashlib.sha256()
        try:
            with open(os.path.join(str(directory), rel), "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        except OSError:
            return False
        if h.hexdigest() != digest:
            return False
    return True


def manifest_step(directory: str) -> Optional[int]:
    try:
        with open(os.path.join(str(directory), "MANIFEST.json")) as f:
            return json.load(f).get("step")
    except (OSError, ValueError):  # ValueError covers JSON errors AND flipped-byte utf-8 tears
        return None


def independent_latest_step(checkpoint_base: str) -> Optional[int]:
    """Newest step among checkpoints that INDEPENDENTLY verify — what a correct
    `resolve("latest")` must land on."""
    best = None
    if not os.path.isdir(checkpoint_base):
        return None
    for name in os.listdir(checkpoint_base):
        path = os.path.join(checkpoint_base, name)
        suffix = name[len("checkpoint_"):] if name.startswith("checkpoint_") else ""
        if not suffix.isdigit() or not os.path.isdir(path):
            continue
        if independent_verify(path):
            step = int(suffix)
            best = step if best is None else max(best, step)
    return best


def params_digest(model) -> str:
    """Content hash of a prepared model's parameters (path-keyed, host-side):
    the resume-exactness fingerprint."""
    from ..checkpointing import _flatten_with_paths

    flat, _ = _flatten_with_paths(model.params)
    h = hashlib.sha256()
    for path, leaf in flat:
        h.update(path.encode())
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def build_train_workload(
    base_dir: str, keep_last_n: int, seed: int, async_save: bool = False,
    mesh_2d: bool = False,
):
    """The canonical tiny train workload — shared by the in-process runner and
    the subprocess `chaos.workload`, so both sides of the supervised story
    exercise (and journal) the same thing. Returns (accelerator, model, opt,
    prepared_dataloader). `async_save=True` arms snapshot-then-commit saves
    (the async-commit-boundary sweeps' workload). `mesh_2d=True` swaps in the
    small MLP on a ("data", "model") mesh with ``sharding_rules="auto"`` and
    Adam — the planner's 2D plan with ZeRO data-sharded moments, so chaos
    faults land on a sharded optimizer state and resumes can assert the
    layout survived (`zero_state_sharded`)."""
    import optax

    from .. import Accelerator, SimpleDataLoader
    from ..data_loader import BatchSampler
    from ..test_utils.training import RegressionDataset, RegressionMLPModel, RegressionModel
    from ..utils import ParallelismConfig, ProjectConfiguration

    accelerator = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(base_dir),
            automatic_checkpoint_naming=True,
            total_limit=keep_last_n,
        ),
        async_save=async_save,
        parallelism_config=ParallelismConfig(data=-1, model=2) if mesh_2d else None,
    )
    n = 16
    data = [RegressionDataset(length=n, seed=seed)[i] for i in range(n)]
    dl = SimpleDataLoader(data, BatchSampler(range(n), 8))
    if mesh_2d:
        bundle = RegressionMLPModel(seed=seed)
        bundle.sharding_rules = "auto"
        tx = optax.adam(0.05)
    else:
        bundle, tx = RegressionModel(), optax.sgd(0.05)
    model, opt, pdl = accelerator.prepare(bundle, tx, dl)
    return accelerator, model, opt, pdl


def opt_state_data_sharded(opt) -> bool:
    """True when some LIVE optimizer-state leaf is sharded along the "data"
    axis — the ZeRO weight-update-sharding layout the 2D planner emits. Read
    off the placed arrays, not the plan: this is the evidence a chaos resume
    journals to prove the layout survived the restore."""
    import jax

    for leaf in jax.tree_util.tree_leaves(getattr(opt, "opt_state", opt)):
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None:
            continue
        for dim in spec:
            axes = dim if isinstance(dim, tuple) else ((dim,) if dim else ())
            if "data" in axes:
                return True
    return False


def stage_layout_evidence(model) -> Dict[str, Any]:
    """The layout record an MPMD pipeline workload journals before any fault
    lands AND after every resume: the (usually NON-uniform) stage->layer
    assignment and per-stage submesh sizes, read off the live model. A
    restart that silently re-planned to a different split — or fell back to
    a single mesh — would train correctly while erasing exactly the layout
    the chaos run exists to stress."""
    counts = [
        len(model.plan.stage_plan.stage_layers(k)) for k in range(model.num_stages)
    ]
    return {
        "num_stages": model.num_stages,
        "stage_layers": counts,
        "nonuniform": len(set(counts)) > 1,
        "submesh_devices": [int(m.devices.size) for m in model.submeshes],
    }


def resume_evidence(
    resolved: str, model, checkpoint_base: str, opt=None
) -> Dict[str, Any]:
    """The journal record both train workloads write after a resume — one
    schema, one producer, so the invariant checks can never diverge between
    the in-process and subprocess paths. Pass ``opt`` on 2D-mesh workloads to
    record whether the restored optimizer state is still ZeRO-sharded along
    "data" (`zero_state_sharded`) — a resume that silently replicates the
    moments would train correctly while spending data_n x the HBM."""
    evidence = {
        "path": resolved,
        "step": manifest_step(resolved),
        "digest": params_digest(model),
        "independently_verified": independent_verify(resolved),
        "expected_step": independent_latest_step(checkpoint_base),
    }
    if opt is not None:
        evidence["zero_state_sharded"] = opt_state_data_sharded(opt)
    return evidence


# ------------------------------------------------------------------ report
@dataclass
class InvariantCheck:
    name: str
    passed: bool
    details: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "passed": self.passed, "details": self.details}


@dataclass
class InvariantReport:
    """The machine-readable outcome of one chaos run: plan, per-invariant
    verdicts, the injection journal, and a registry snapshot (chaos counters +
    whatever the workload instrumented)."""

    plan: dict
    workload: str
    checks: List[InvariantCheck] = field(default_factory=list)
    injections: List[dict] = field(default_factory=list)
    metrics: List[dict] = field(default_factory=list)
    #: Tagged runner diagnostics that are not invariant verdicts — e.g.
    #: ``{"tag": "crash_loop", ...}`` when a sweep was cut short because the
    #: workload made no forward progress across restarts (the async at_step
    #: SIGKILL livelock): the report says WHY it stopped instead of burning
    #: the whole restart budget on a deterministic loop.
    diagnostics: List[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(c.passed for c in self.checks)

    @property
    def violated(self) -> List[InvariantCheck]:
        return [c for c in self.checks if not c.passed]

    def to_dict(self) -> dict:
        return {
            "format": 1,
            "plan": self.plan,
            "workload": self.workload,
            "ok": self.ok,
            "checks": [c.to_dict() for c in self.checks],
            "injections": self.injections,
            "metrics": self.metrics,
            "diagnostics": self.diagnostics,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def save(self, path: str) -> str:
        with open(str(path), "w") as f:
            f.write(self.to_json())
        return str(path)

    @classmethod
    def from_dict(cls, data: dict) -> "InvariantReport":
        return cls(
            plan=data.get("plan", {}),
            workload=data.get("workload", "?"),
            checks=[
                InvariantCheck(c["name"], bool(c["passed"]), c.get("details", {}))
                for c in data.get("checks", [])
            ],
            injections=data.get("injections", []),
            metrics=data.get("metrics", []),
            diagnostics=data.get("diagnostics", []),
        )

    @classmethod
    def load(cls, path: str) -> "InvariantReport":
        with open(str(path)) as f:
            return cls.from_dict(json.load(f))

    def render_text(self) -> str:
        lines = [
            f"chaos run: plan={self.plan.get('name', '?')} workload={self.workload} "
            f"injections={len(self.injections)} -> {'OK' if self.ok else 'INVARIANTS VIOLATED'}"
        ]
        for check in self.checks:
            mark = "ok " if check.passed else "FAIL"
            lines.append(f"  [{mark}] {check.name}")
            if not check.passed:
                for key, value in sorted(check.details.items()):
                    lines.append(f"         {key}: {value}")
        counts: Dict[str, int] = {}
        for entry in self.injections:
            counts[entry["kind"]] = counts.get(entry["kind"], 0) + 1
        for kind in sorted(counts):
            lines.append(f"  injected {kind} x{counts[kind]}")
        for diag in self.diagnostics:
            detail = " ".join(f"{k}={v}" for k, v in sorted(diag.items()) if k != "tag")
            lines.append(f"  diagnostic [{diag.get('tag', '?')}] {detail}")
        return "\n".join(lines)


# ------------------------------------------------------------------ runner
class ChaosRunner:
    """Execute a workload under a `FaultPlan` and check the recovery invariants."""

    def __init__(
        self,
        plan: FaultPlan,
        registry: Optional[MetricsRegistry] = None,
        clock=None,
        trace_dir: Optional[str] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.plan = plan
        # Every chaos run records a timeline: injections land as `chaos.*`
        # trace events, workload attempts/steps as spans. With a `trace_dir`
        # the recorder streams span JSONL there (and the supervised workload
        # inherits the dir through the env protocol), so `accelerate-tpu
        # trace dump` renders the sweep as one Perfetto timeline; without one
        # the in-memory ring still backs the trace_complete invariant.
        self.trace_dir = str(trace_dir) if trace_dir else None
        if tracer is None:
            tracer = Tracer(
                recorder=FlightRecorder(log_dir=self.trace_dir), category="chaos"
            )
        self.tracer = tracer
        self.session = ChaosSession(plan, registry=registry, clock=clock, tracer=tracer)

    # ---------------------------------------------------------------- train
    def run_train(
        self,
        base_dir: str,
        steps: int = 8,
        max_restarts: int = 16,
        keep_last_n: int = 3,
        downtime_budget_s: float = 5.0,
        async_save: bool = False,
        no_progress_threshold: int = 6,
    ) -> InvariantReport:
        """In-process supervised train loop: RegressionModel, one checkpoint per
        step, chaos polled at every boundary. An `InjectedKill` ends an attempt
        exactly like a SIGKILL ends a process (no cleanup runs in the workload);
        the runner then 'respawns' — fresh Accelerator, resume from latest —
        until the run completes or the restart budget is spent.

        `async_save=True` runs every save through the snapshot-then-commit
        background committer: a kill while a commit is in flight ABORTS the
        commit before 'respawning' (a dead process cannot publish), a committer
        that dies of an injected kill surfaces at the next step boundary
        exactly like a process death, and an ordinary commit failure (EIO
        retries exhausted) surfaces as `CheckpointCommitError` on the next
        save's barrier — counted as a crash, restarted, and the previously
        published checkpoint must still resolve.

        `no_progress_threshold`: after that many CONSECUTIVE restarts with no
        new independently-verified checkpoint published (the same step being
        killed over and over — e.g. an every-match `at_step` SIGKILL whose
        async commit can never publish), the runner stops sweeping and tags a
        ``crash_loop`` diagnostic instead of spending the whole restart budget
        on a deterministic livelock. The default leaves headroom for
        legitimate transient-fault storms (a several-retry EIO burst clears
        after a few fruitless restarts and must not be cut short); 0 disables
        the detector."""
        from ..checkpointing import CheckpointCommitError

        journal: Dict[str, Any] = {
            "attempts": 0, "graceful_exits": 0, "commit_failures": 0,
            "saves": [], "intents": [], "resumes": [],
        }
        ledger: Dict[str, float] = {}
        restarts = 0
        downtime_s = 0.0
        completed = False
        checkpoint_base = os.path.join(str(base_dir), "checkpoints")
        diagnostics: List[dict] = []
        last_progress = independent_latest_step(checkpoint_base)
        no_progress = 0
        boundary = StepBoundaryInjector(self.session, hard=False)
        with FilesystemInjector(self.session), HarnessInjector(self.session):
            while True:
                journal["attempts"] += 1
                attempt_span = self.tracer.start_span(
                    "train.attempt", category="train", attempt=journal["attempts"]
                )
                try:
                    with self.tracer.activate(attempt_span):
                        self._train_attempt(
                            base_dir, steps, keep_last_n, boundary, journal, ledger,
                            async_save=async_save,
                        )
                    attempt_span.annotate(outcome="completed").end()
                    completed = True
                    break
                except InjectedKill:
                    # hard kill: nothing in the attempt got to clean up. The
                    # crash boundary is a standalone event (streamed, were this
                    # a real process, BEFORE the respawn) — what the stitched
                    # timeline anchors the restart on.
                    attempt_span.annotate(outcome="killed").end()
                    self.tracer.event(
                        "chaos.crash_boundary", category="chaos",
                        attempt=journal["attempts"], kind="sigkill",
                    )
                except CheckpointCommitError:
                    # A failed (not killed) background commit surfaced at the
                    # barrier: production's train loop crashes on it and the
                    # supervisor restarts — the runner plays both parts.
                    journal["commit_failures"] += 1
                    attempt_span.annotate(outcome="commit_failed").end()
                    self.tracer.event(
                        "chaos.crash_boundary", category="chaos",
                        attempt=journal["attempts"], kind="commit_failure",
                    )
                except _GracefulPreemption:
                    attempt_span.annotate(outcome="preempted").end()
                    self.tracer.event(
                        "chaos.crash_boundary", category="chaos",
                        attempt=journal["attempts"], kind="sigterm",
                    )
                    journal["graceful_exits"] += 1
                restarts += 1
                if restarts > max_restarts:
                    break
                # No-forward-progress detection: a restart that resumes with
                # the SAME newest verified checkpoint as the last one made no
                # progress; K in a row is a livelock, not a recovery chain.
                progress = independent_latest_step(checkpoint_base)
                if progress == last_progress:
                    no_progress += 1
                else:
                    no_progress = 0
                last_progress = progress
                if no_progress_threshold and no_progress >= no_progress_threshold:
                    diagnostics.append({
                        "tag": "crash_loop",
                        "why": "no_forward_progress",
                        "restarts_without_new_checkpoint": no_progress,
                        "stuck_at_verified_step": progress,
                        "restarts": restarts,
                    })
                    logger.error(
                        "chaos: CRASH LOOP — %d consecutive restarts with no new "
                        "published checkpoint (stuck at verified step %s); stopping "
                        "the sweep. diagnostic=crash_loop",
                        no_progress, progress,
                    )
                    break
                backoff = min(0.01 * restarts, 0.05)
                self.session.clock.sleep(backoff)
                downtime_s += backoff
        checks = [
            self._check_resume_exactness(journal),
            self._check_no_torn_resolved(journal, checkpoint_base),
            self._check_restart_budget(completed, restarts, max_restarts, downtime_s,
                                       downtime_budget_s),
            self._check_ledger_reconciles(ledger, journal, async_save=async_save),
            self._check_trace_complete(journal),
        ]
        return self._report(
            "async-train" if async_save else "train", checks, diagnostics=diagnostics
        )

    def _train_attempt(
        self,
        base_dir: str,
        steps: int,
        keep_last_n: int,
        boundary: StepBoundaryInjector,
        journal: Dict[str, Any],
        ledger: Dict[str, float],
        async_save: bool = False,
    ):
        accelerator, model, opt, pdl = build_train_workload(
            base_dir, keep_last_n, self.plan.seed, async_save=async_save
        )
        handler = accelerator.register_preemption_checkpoint(exit_on_save=False)
        stream = None
        finished_cleanly = False
        try:
            manager = accelerator.checkpoint_manager()
            start_step = 0
            try:
                resolved = manager.resolve("latest")
            except FileNotFoundError:
                resolved = None
            if resolved is not None:
                accelerator.load_state("latest")
                evidence = resume_evidence(resolved, model, manager.base_dir)
                journal["resumes"].append({"attempt": journal["attempts"], **evidence})
                resumed_step = evidence["step"]
                start_step = (resumed_step if resumed_step is not None else -1) + 1
                self.tracer.event(
                    "train.resume", category="train",
                    attempt=journal["attempts"], step=resumed_step,
                )

            def batches():
                while True:
                    for b in pdl:
                        yield b

            stream = batches()
            for step in range(start_step, steps):
                with self.tracer.span("train.step", category="train", step=step):
                    batch = next(stream)
                    accelerator.backward(model.loss, batch)
                    opt.step()
                    opt.zero_grad()
                    digest = params_digest(model)
                    # Intent BEFORE the save: a kill after the directory rename
                    # but before save_state returns leaves a committed
                    # checkpoint the journal would otherwise not know the
                    # digest of.
                    intended_step = accelerator.save_iteration
                    journal["intents"].append(
                        {"step": intended_step, "digest": digest}
                    )
                    path = accelerator.save_state()
                    journal["saves"].append({
                        "attempt": journal["attempts"],
                        # An async save's manifest does not exist yet when
                        # save_state returns — the intended step is the record
                        # (the intent above already carries the same pair).
                        "step": intended_step if async_save else manifest_step(path),
                        "digest": digest,
                        "path": path,
                    })
                # Chaos fires AT the boundary, outside the step span: a kill
                # here models SIGKILL-between-steps, not a mid-step death.
                boundary.poll(step)
                # A background committer that died of an injected kill is a
                # process death: surface it at the boundary, like a SIGKILL.
                accelerator.poll_async_checkpoint()
                if handler.preemption_requested:
                    raise _GracefulPreemption()
            # A completed run's final commit must land (or surface its failure)
            # before the attempt is declared done.
            accelerator.drain_checkpoints()
            finished_cleanly = True
        finally:
            if stream is not None:
                # A kill mid-iteration leaves the loader generator suspended;
                # close it here instead of letting GC tear it down mid-suite.
                stream.close()
            if not finished_cleanly:
                # Process-death semantics for the background committer: a dead
                # process cannot publish. Abort the in-flight commit (it stops
                # at the next phase boundary, leaving only staging litter) and
                # join without raising — the attempt is already dying of the
                # original kill.
                accelerator.abort_async_checkpoint()
            for cause, seconds in accelerator.timeline.goodput()["lost_s"].items():
                ledger[cause] = ledger.get(cause, 0.0) + seconds
            commit_hist = getattr(accelerator, "_m_ckpt_commit_seconds", None)
            if commit_hist is not None and commit_hist.count:
                ledger["checkpoint_async_commit"] = (
                    ledger.get("checkpoint_async_commit", 0.0) + commit_hist.sum
                )
            handler.uninstall()

    # ---------------------------------------------------------------- supervised train
    def run_supervised_train(
        self,
        base_dir: str,
        steps: int = 5,
        max_restarts: int = 4,
        downtime_budget_s: float = 30.0,
        async_save: bool = False,
        no_progress_threshold: int = 6,
        mesh_2d: bool = False,
    ) -> InvariantReport:
        """The end-to-end path: the real `Supervisor` restarting a real
        subprocess workload (`python -m accelerate_tpu.chaos.workload`), the
        plan propagated through ``ACCELERATE_TPU_FAULT_PLAN`` exactly as
        `accelerate-tpu launch --fault_plan` would. With `async_save` the
        workload saves through the background committer and a `proc.sigkill`
        is a REAL SIGKILL — a commit genuinely in flight dies mid-write, the
        strongest form of the kill-during-background-commit sweep."""
        from ..fault_tolerance import PREEMPTED_EXIT_CODE, Supervisor

        base_dir = str(base_dir)
        os.makedirs(base_dir, exist_ok=True)
        plan_path = self.plan.save(os.path.join(base_dir, "fault_plan.json"))
        env = dict(os.environ)
        env[FAULT_PLAN_ENV] = plan_path
        env.setdefault("JAX_PLATFORMS", "cpu")
        cmd = [
            sys.executable, "-m", "accelerate_tpu.chaos.workload",
            "--base-dir", base_dir, "--steps", str(steps),
        ] + (["--async-save"] if async_save else []) + (
            ["--mesh-2d"] if mesh_2d else []
        )
        # A clean preemption handoff (exit 143) ENDS supervision by design —
        # in production the scheduler respawns the whole job. The runner plays
        # the scheduler: re-run the supervisor after each handoff (counted
        # against the same budget) until the workload completes or fails.
        restarts = 0
        preemption_handoffs = 0
        downtime_s = 0.0
        crash_loop = False
        crash_loop_reason = None
        checkpoint_base = os.path.join(base_dir, "checkpoints")
        while True:
            supervisor = Supervisor(
                cmd,
                env=env,
                max_restarts=max_restarts - restarts,
                grace_period=30.0,
                backoff_seconds=0.05,
                max_backoff_seconds=0.2,
                monitor_interval=0.05,
                crash_loop_min_uptime=0.0,  # every attempt imports jax; uptime is not a crash signal here
                # No-forward-progress detection: each subprocess attempt
                # re-arms the plan from env, so an every-attempt at_step kill
                # whose (async) checkpoint can never publish would otherwise
                # re-kill the SAME step until the budget burns — the newest
                # independently-verified checkpoint is the progress token.
                # Same headroom rationale as run_train's default: a transient
                # fault storm may burn a few attempts before the first publish
                # and must not be cut short.
                progress_fn=lambda: independent_latest_step(checkpoint_base),
                no_progress_threshold=no_progress_threshold,
                # Attempt spans + trace-context injection: each child re-arms
                # via Tracer.from_env and parents its spans under the attempt
                # that spawned it — the restart chain stitches into ONE trace.
                tracer=self.tracer,
            )
            code = supervisor.run()
            restarts += supervisor.restart_count
            downtime_s += supervisor.downtime_s
            crash_loop = crash_loop or supervisor.crash_loop_detected
            crash_loop_reason = crash_loop_reason or supervisor.crash_loop_reason
            if supervisor.crash_loop_detected:
                break
            if code == PREEMPTED_EXIT_CODE and preemption_handoffs + restarts < max_restarts:
                preemption_handoffs += 1
                continue
            break
        journal = self._read_workload_journal(base_dir)
        diagnostics: List[dict] = []
        if crash_loop:
            diagnostics.append({
                "tag": "crash_loop",
                "why": crash_loop_reason or "unknown",
                "restarts": restarts,
                "stuck_at_verified_step": independent_latest_step(checkpoint_base),
            })
        checks = [
            self._check_resume_exactness(journal),
            self._check_no_torn_resolved(journal, checkpoint_base),
            InvariantCheck(
                "supervisor",
                passed=code == 0 and restarts + preemption_handoffs <= max_restarts
                and downtime_s <= downtime_budget_s,
                details={
                    "exit_code": code,
                    "restarts": restarts,
                    "preemption_handoffs": preemption_handoffs,
                    "max_restarts": max_restarts,
                    "downtime_s": round(downtime_s, 6),
                    "downtime_budget_s": downtime_budget_s,
                    "crash_loop_detected": crash_loop,
                    "crash_loop_reason": crash_loop_reason,
                },
            ),
        ]
        # The workload's own injections happened in child processes; fold its
        # journal into ours so the report still carries them.
        if mesh_2d:
            checks.append(self._check_zero_state_sharded(journal))
        for entry in journal.get("injections", []):
            self.session.injections.append(entry)
            self.session.registry.counter(
                "chaos_injected_total",
                help="faults injected by the chaos subsystem, by kind",
                labels={"kind": entry["kind"]},
            ).inc()
        checks.append(self._check_trace_complete(journal, supervised=True))
        return self._report("supervised-train", checks, diagnostics=diagnostics)

    @staticmethod
    def _read_workload_journal(base_dir: str) -> Dict[str, Any]:
        journal: Dict[str, Any] = {
            "attempts": 0, "graceful_exits": 0, "saves": [], "intents": [],
            "resumes": [], "injections": [], "layouts": [],
        }
        path = os.path.join(str(base_dir), "chaos_journal.jsonl")
        if not os.path.isfile(path):
            return journal
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a torn final line from a killed writer
                rtype = record.pop("type", None)
                if rtype == "attempt":
                    journal["attempts"] += 1
                elif rtype == "graceful_exit":
                    journal["graceful_exits"] += 1
                elif rtype in ("save", "intent", "resume", "injection", "layout"):
                    journal[rtype + "s"].append(record)
        return journal

    # ---------------------------------------------------------------- serve
    def run_serve(
        self,
        num_requests: int = 8,
        num_slots: int = 2,
        chunk_size: int = 4,
        max_queue: int = 4,
        max_new_tokens: int = 4,
        max_cycles: int = 200,
        paged: bool = True,
        speculative: bool = False,
        attention_impl: str = "xla",
        kv_cache_dtype: str = "bf16",
        tp: int = 1,
    ) -> InvariantReport:
        """Serving workload: a tiny llama `ContinuousBatcher` fed one request
        per cycle (plus scripted queue bursts), driven to drain under injected
        dispatch stalls/failures. Chaos shares the engine's metrics registry so
        the report's snapshot carries both. `speculative=True` runs the same
        sweeps through the draft/verify chunk (draft window in every admission,
        history mirror in every blast-radius rebuild), so recovery is proven to
        reconstruct the speculative state too. `attention_impl="pallas_paged"`
        drives the sweeps through the fused page-walk kernels
        (ops/paged_attention): blast-radius recovery must rebuild the
        kernel-path executables identically — same invariants, no retrace.
        `kv_cache_dtype="int8"`/`"fp8_e4m3"` runs the sweeps on the QUANTIZED
        page pool: the blast-radius rebuild must recreate the quantized pools
        AND their scale pools from zeros, and the page ledger must still
        close — fault paths exercise the quantized cache, not just happy
        decode. `tp=N` spans the engine over an N-device submesh: the same
        sweeps must leave the rebuilt pools (and scale pools) SHARDED on
        that submesh — the extra `tp_pool_sharded` invariant fails if a
        blast-radius recovery quietly rebuilt them replicated."""
        from ..models.llama import LlamaConfig, create_llama_model
        from ..serving import FINISH_REASONS, ContinuousBatcher, QueueFull, Request

        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_theta=10000.0,
        )
        model = create_llama_model(cfg, seq_len=32)
        # Paged (default): page_size=4 with a shared 8-token system prompt on
        # half the traffic, so the dispatch-failure sweeps exercise page
        # refcounts AND live prefix registrations — the page-ledger invariant
        # below is non-vacuous. paged=False drives the same sweeps through the
        # contiguous fallback layout (its blast-radius recovery stays covered).
        engine = ContinuousBatcher(
            model, num_slots=num_slots, max_length=64, chunk_size=chunk_size,
            max_queue=max_queue, registry=self.session.registry,
            tracer=self.tracer, paged=paged, page_size=4,
            speculative=speculative, draft_tokens=3,
            attention_impl=attention_impl, kv_cache_dtype=kv_cache_dtype,
            tp=tp,
        )
        ServingInjector(self.session).arm(engine)
        rng = np.random.default_rng(self.plan.seed)
        shared_prefix = rng.integers(1, cfg.vocab_size, (8,)).astype(np.int32)

        next_id = 0
        rejected = 0
        accepted: List[int] = []
        first_id_after_error: Optional[int] = None

        def make_request() -> Request:
            nonlocal next_id
            prompt = rng.integers(1, cfg.vocab_size, (int(rng.integers(2, 9)),)).astype(np.int32)
            if rng.integers(2):
                prompt = np.concatenate([shared_prefix, prompt])
            request = Request(next_id, prompt, max_new_tokens=max_new_tokens)
            next_id += 1
            return request

        def submit_one() -> bool:
            nonlocal rejected
            request = make_request()
            try:
                engine.submit(request)
            except QueueFull:
                rejected += 1
                return False
            accepted.append(request.request_id)
            return True

        # After a dispatch failure's blast radius, the recovery invariant needs
        # live evidence: keep the workload submitting a couple of fresh probe
        # requests past the failure so "the engine still serves" is observed,
        # not assumed.
        error_kinds = ("serve.dispatch_error", "serve.insert_error")
        recovery_probes = 2 if any(ev.kind in error_kinds for ev in self.plan.events) else 0
        probes_sent = 0
        errors_before = 0
        cycles = 0
        stalled = False
        while (
            len(accepted) < num_requests
            or engine.pending
            or (first_id_after_error is not None and probes_sent < recovery_probes)
        ):
            if cycles >= max_cycles:
                stalled = True
                break
            if len(accepted) < num_requests:
                submit_one()
            elif first_id_after_error is not None and probes_sent < recovery_probes:
                if submit_one():
                    probes_sent += 1
            for ev in self.session.fire("serve.queue_burst", step=cycles):
                for _ in range(int(ev.args.get("count", 8))):
                    submit_one()
            engine.step()
            error_count = sum(
                1 for e in self.session.injections if e["kind"] in error_kinds
            )
            if error_count > errors_before and first_id_after_error is None:
                first_id_after_error = next_id
            errors_before = error_count
            cycles += 1
        results = dict(engine.drain())
        engine.close()

        finish_reasons = {
            rid: results[rid].finish_reason if rid in results else None for rid in accepted
        }
        non_terminal = {
            rid: reason for rid, reason in finish_reasons.items()
            if reason not in FINISH_REASONS
        }
        checks = [
            InvariantCheck(
                "terminal_finish_reasons",
                passed=not non_terminal and not stalled,
                details={
                    "accepted": len(accepted), "rejected_queue_full": rejected,
                    "non_terminal": non_terminal, "stalled": stalled, "cycles": cycles,
                },
            ),
            InvariantCheck(
                "queue_bounded",
                passed=int(engine.stats["queue_peak"]) <= max_queue,
                details={"queue_peak": int(engine.stats["queue_peak"]), "max_queue": max_queue},
            ),
            self._check_engine_recovered(finish_reasons, first_id_after_error),
            self._check_serve_ledger(engine, accepted),
            self._check_page_ledger(engine),
            self._check_serve_trace(accepted),
        ]
        if tp > 1:
            checks.append(self._check_tp_pool_sharded(engine, tp))
        return self._report("serve", checks)

    def _check_tp_pool_sharded(self, engine, tp: int) -> InvariantCheck:
        """Mesh-spanning engines: the LIVE slot cache — including one rebuilt
        by a blast-radius recovery mid-sweep — must still be sharded over the
        `tp`-device submesh (K/V pools and quantized scale pools carry the
        "model" axis; a silently-replicated rebuild would serve correctly
        while spending N x the HBM, which is exactly the failure chaos is
        here to catch)."""
        import jax

        unsharded = []
        sharded = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(engine._cache)[0]:
            name = str(getattr(path[-1], "key", getattr(path[-1], "name", path[-1])))
            if name not in ("cached_key", "cached_value", "key_scale", "value_scale"):
                continue
            spec = getattr(getattr(leaf, "sharding", None), "spec", None)
            if spec is None or "model" not in tuple(spec):
                unsharded.append("/".join(str(getattr(k, "key", k)) for k in path))
            else:
                sharded += 1
        mesh_ok = engine.mesh is not None and engine.mesh.devices.size == tp
        return InvariantCheck(
            "tp_pool_sharded",
            passed=mesh_ok and sharded > 0 and not unsharded,
            details={
                "tp": tp, "mesh_devices": int(engine.mesh.devices.size) if engine.mesh else 0,
                "sharded_leaves": sharded, "unsharded_leaves": unsharded,
            },
        )

    # ---------------------------------------------------------------- router
    def run_router(
        self,
        num_requests: int = 12,
        replicas: int = 3,
        num_slots: int = 2,
        chunk_size: int = 4,
        max_queue: int = 8,
        max_new_tokens: int = 4,
        max_cycles: int = 400,
        hedge_after_s: Optional[float] = None,
    ) -> InvariantReport:
        """Replicated-fleet workload: a `router.Router` over N in-process
        engines fed one request per cycle, driven to drain while the
        `RouterInjector` kills / stalls / poisons individual replicas
        mid-traffic. The machine-checked invariants:

          - **terminal_finish_reasons** — every accepted request reaches a
            terminal reason from `ROUTER_FINISH_REASONS` (``replica_lost``
            included) and the workload drains without stalling;
          - **no_duplicate_streams** — the concatenation of every stream event
            the router forwarded for a request equals that request's final
            token list EXACTLY (a retried or hedged request can never deliver
            a token twice);
          - **fleet_recovered** — requests submitted AFTER the first injected
            replica fault still complete normally, and a killed replica is
            back in a routable state by drain;
          - **no_route_to_ejected** — the routing journal contains no decision
            that placed work on a replica while it was ejected (or draining);
          - **ledger_reconciles** — chaos counters match the injection journal
            and `router_retries_total` matches the routing journal's retries.
        """
        from ..models.llama import LlamaConfig, create_llama_model
        from ..router import ROUTER_FINISH_REASONS, Router
        from ..serving import QueueFull, Request

        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_theta=10000.0,
        )
        model = create_llama_model(cfg, seq_len=32)
        router = Router(
            model, replicas=replicas, num_slots=num_slots, max_length=64,
            chunk_size=chunk_size, max_queue=max_queue, default_deadline_s=60.0,
            hedge_after_s=hedge_after_s, registry=self.session.registry,
            tracer=self.tracer, paged=True, page_size=4,
            rejoin_cooldown_s=0.05, probation_steps=2, stall_degrade_s=None,
        )
        RouterInjector(self.session).arm(router)
        rng = np.random.default_rng(self.plan.seed)

        next_id = 0
        rejected = 0
        accepted: List[int] = []
        streamed: Dict[int, List[int]] = {}
        first_id_after_fault: Optional[int] = None

        def submit_one() -> bool:
            nonlocal next_id, rejected
            prompt = rng.integers(1, cfg.vocab_size, (int(rng.integers(2, 9)),)).astype(np.int32)
            request = Request(next_id, prompt, max_new_tokens=max_new_tokens)
            next_id += 1
            try:
                router.submit(request)
            except QueueFull:
                rejected += 1
                return False
            accepted.append(request.request_id)
            streamed[request.request_id] = []
            return True

        router_kinds = ("router.replica_kill", "router.replica_stall", "router.replica_poison")
        fault_planned = any(ev.kind in router_kinds for ev in self.plan.events)
        recovery_probes = 3 if fault_planned else 0
        probes_sent = 0
        faults_before = 0
        cycles = 0
        stalled = False
        while (
            len(accepted) < num_requests
            or router.pending
            or (first_id_after_fault is not None and probes_sent < recovery_probes)
        ):
            if cycles >= max_cycles:
                stalled = True
                break
            if len(accepted) < num_requests:
                submit_one()
            elif first_id_after_fault is not None and probes_sent < recovery_probes:
                if submit_one():
                    probes_sent += 1
            for ev in self.session.fire("serve.queue_burst", step=cycles):
                for _ in range(int(ev.args.get("count", 8))):
                    submit_one()
            for rid, toks in router.step():
                if rid in streamed:
                    streamed[rid].extend(toks)
            fault_count = sum(
                1 for e in self.session.injections if e["kind"] in router_kinds
            )
            if fault_count > faults_before and first_id_after_fault is None:
                first_id_after_fault = next_id
            faults_before = fault_count
            cycles += 1
        results = dict(router.drain())
        # Recovery phase: a replica killed late in the run is still inside its
        # rejoin cooldown when the traffic drains — keep cycling (bounded)
        # until the health machine brings every replica back, so
        # `fleet_recovered` measures actual recovery, not drain timing.
        while (
            any(s == "ejected" for s in router.replica_states.values())
            and cycles < max_cycles
        ):
            self.session.clock.sleep(0.01)
            router.step()
            cycles += 1
        for _ in range(router.replica_set.probation_steps + 1):
            router.step()
        final_states = dict(router.replica_states)
        routing_log = list(router.routing_log)
        state_log = list(router.replica_set.state_log)
        retries_counter = int(router.stats["retries"])
        router.close()

        finish_reasons = {
            rid: results[rid].finish_reason if rid in results else None for rid in accepted
        }
        non_terminal = {
            rid: reason for rid, reason in finish_reasons.items()
            if reason not in ROUTER_FINISH_REASONS
        }
        duplicate_streams = {
            rid: {"streamed": streamed[rid], "result": list(results[rid].tokens)}
            for rid in accepted
            if rid in results and streamed[rid] != list(results[rid].tokens)
        }
        checks = [
            InvariantCheck(
                "terminal_finish_reasons",
                passed=not non_terminal and not stalled,
                details={
                    "accepted": len(accepted), "rejected_queue_full": rejected,
                    "non_terminal": non_terminal, "stalled": stalled, "cycles": cycles,
                    "reasons": _reason_counts(finish_reasons),
                },
            ),
            InvariantCheck(
                "no_duplicate_streams",
                passed=not duplicate_streams,
                details={"mismatched": duplicate_streams},
            ),
            self._check_fleet_recovered(
                finish_reasons, first_id_after_fault, final_states, fault_planned
            ),
            self._check_no_route_to_ejected(routing_log, state_log),
            self._check_router_ledger(routing_log, retries_counter, accepted, finish_reasons),
        ]
        return self._report("router", checks)

    # ---------------------------------------------------------------- fleet
    def run_fleet(
        self,
        num_requests: int = 10,
        replicas: int = 2,
        num_slots: int = 2,
        chunk_size: int = 4,
        max_queue: int = 8,
        max_new_tokens: int = 4,
        max_cycles: int = 2000,
        autoscale: bool = True,
        step_timeout_s: float = 15.0,
        workdir: Optional[str] = None,
        transport: str = "pipe",
        reconnect_deadline_s: float = 8.0,
    ) -> InvariantReport:
        """Out-of-process fleet workload: a `Router` over REAL subprocess
        engine workers (`worker.SubprocessEngine` via `make_subprocess_factory`)
        driven to drain while the env-propagated plan SIGKILLs and stalls the
        worker PROCESSES themselves mid-traffic. The PR 10 router invariants
        are re-checked against true process fault domains, plus two new ones:

          - **worker_restart_rejoins_warm** — every observed worker death (pid
            change on a replica) was followed by a respawned process whose
            ready handshake reports a pre-warmed insert ladder, and the fleet
            ends with every non-retired replica routable;
          - **autoscaler_converges** (``autoscale=True``) — the queue-burst
            pressure scales the fleet up past its floor, and after the traffic
            drains the autoscaler retires the extra workers back to the floor.

        With ``transport="socket"`` the workers serve over TCP and the plan may
        carry ``net.*`` faults (injected controller-side at the transport seam
        via `TransportInjector`), adding two network invariants:

          - **reconnect_reconciles** — the controller's successful-reconnect
            counters are fully accounted by the workers' re-registration
            journal (every reconnect the controller counted, some worker
            accepted under a bumped epoch);
          - **partition_is_not_death** — a healed partition must NOT change any
            worker's pid (reconnect, not respawn); only a partition window
            past ``reconnect_deadline_s`` may escalate to the respawn path,
            and then it MUST.

        Worker-side injections are journaled (append+fsync, BEFORE the kill
        lands) to a shared journal the ledger invariant reconciles against
        observed process deaths — and that restarted workers read back so a
        re-armed plan cannot livelock by re-killing at the same trigger."""
        import tempfile

        from ..models.llama import LlamaConfig, create_llama_model
        from ..router import ROUTER_FINISH_REASONS, Router
        from ..serving import QueueFull, Request
        from ..worker import CHAOS_JOURNAL_ENV, make_subprocess_factory
        from .injectors import TransportInjector
        from .plan import FAULT_PLAN_ENV

        net_kinds = ("net.partition", "net.slow", "net.flap")
        net_events = [ev for ev in self.plan.events if ev.kind in net_kinds]
        if net_events and transport != "socket":
            raise ValueError(
                "net.* faults inject at the socket-transport seam: run the "
                "fleet workload with transport='socket' (the pipe transport "
                "has no reconnectable link to partition)"
            )

        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_theta=10000.0,
        )
        model = create_llama_model(cfg, seq_len=32)
        workdir = workdir or tempfile.mkdtemp(prefix="accelerate_tpu_chaos_fleet_")
        journal_path = os.path.join(workdir, "fleet_chaos_journal.jsonl")
        worker_env = dict(os.environ)
        worker_env[FAULT_PLAN_ENV] = self.plan.to_json(indent=None)
        worker_env[CHAOS_JOURNAL_ENV] = journal_path
        if self.trace_dir:
            worker_env["ACCELERATE_TPU_TRACE_DIR"] = self.trace_dir
        factory = make_subprocess_factory(
            model,
            engine_kwargs=dict(
                num_slots=num_slots, max_length=64, chunk_size=chunk_size,
                max_queue=max_queue, paged=True, page_size=4,
            ),
            workdir=workdir, env=worker_env, step_timeout_s=step_timeout_s,
            transport=transport,
            reconnect_deadline_s=(
                reconnect_deadline_s if transport == "socket" else None
            ),
        )
        router = Router(
            model, replicas=replicas, max_queue=max_queue, default_deadline_s=120.0,
            registry=self.session.registry, tracer=self.tracer,
            engine_factory=factory,
            rejoin_cooldown_s=0.05, probation_steps=2, stall_degrade_s=None,
            heartbeat_timeout_s=None,  # hang detection is the client step timeout
            **(dict(
                min_replicas=replicas, max_replicas=replicas + 1,
                autoscale_queue_high=1.5, autoscale_cooldown_s=0.0,
                idle_retire_s=0.05,
            ) if autoscale else {}),
        )
        if net_events:
            # Net faults damage the controller-side transport seam (sever the
            # link, delay/tear frames) — arm the wrapper on every engine the
            # router builds, including respawns.
            TransportInjector(self.session).arm(router)
        rng = np.random.default_rng(self.plan.seed)

        next_id = 0
        rejected = 0
        accepted: List[int] = []
        streamed: Dict[int, List[int]] = {}
        first_id_after_fault: Optional[int] = None
        #: replica index -> [(pid, warm_handshake)] in observation order.
        pids_seen: Dict[int, List[tuple]] = {}
        peak_active = router.active_replicas

        def observe_fleet():
            nonlocal peak_active
            peak_active = max(peak_active, router.active_replicas)
            for replica in router.replica_set.replicas:
                if replica.dead or replica.state == "retired":
                    continue
                engine = replica.engine
                pid = getattr(engine, "pid", None)
                seen = pids_seen.setdefault(replica.index, [])
                if pid is not None and (not seen or seen[-1][0] != pid):
                    ready = getattr(engine, "ready_info", {}) or {}
                    seen.append((pid, bool(ready.get("warm"))))

        def submit_one() -> bool:
            nonlocal next_id, rejected
            prompt = rng.integers(1, cfg.vocab_size, (int(rng.integers(2, 9)),)).astype(np.int32)
            request = Request(next_id, prompt, max_new_tokens=max_new_tokens)
            next_id += 1
            try:
                router.submit(request)
            except QueueFull:
                rejected += 1
                return False
            accepted.append(request.request_id)
            streamed[request.request_id] = []
            return True

        fleet_kinds = ("fleet.worker_kill", "fleet.worker_stall")
        planned_faults = sum(
            max(ev.times, 1) for ev in self.plan.events if ev.kind in fleet_kinds
        )
        planned_net = sum(max(ev.times, 1) for ev in net_events)
        #: A partition/flap window longer than the reconnect budget MUST
        #: escalate to the respawn path; anything shorter must heal in place.
        _net_windows = {"net.partition": 0.5, "net.flap": 0.1}
        escalation_expected = any(
            ev.kind in _net_windows
            and float(ev.args.get("window_s", _net_windows[ev.kind]))
            > reconnect_deadline_s
            for ev in net_events
        )
        fault_planned = (planned_faults + planned_net) > 0
        recovery_probes = 3 if fault_planned else 0
        #: Worker faults fire IN the workers (env-propagated plan, their own
        #: step-op call counts) and are journaled BEFORE the damage lands, so
        #: the journal — not a controller-side proxy like ejection counts,
        #: which a flapping rejoin could inflate — is the ground truth for
        #: "every planned fault actually fired". Traffic keeps flowing
        #: (bounded) until it says so; a sweep that never exercised its
        #: faults must go red, not green.
        hard_cap = max(num_requests * 8, num_requests + 32)
        planned_total = planned_faults + planned_net

        def faults_landed() -> int:
            # Worker faults land in the worker journal; net faults fire
            # controller-side at the transport seam and land in the session's
            # own injection counters.
            worker_side = sum(
                1 for e in self._read_fleet_journal(journal_path)
                if e.get("kind") in fleet_kinds
            )
            counts = self.session.counts()
            net_side = sum(counts.get(kind, 0) for kind in net_kinds)
            return worker_side + net_side

        probes_sent = 0
        faults_before = 0
        cycles = 0
        stalled = False
        observe_fleet()
        while (
            len(accepted) < num_requests
            or router.pending
            or (fault_planned and faults_landed() < planned_total
                and len(accepted) < hard_cap)
            or (first_id_after_fault is not None and probes_sent < recovery_probes)
        ):
            if cycles >= max_cycles:
                stalled = True
                break
            if len(accepted) < num_requests:
                submit_one()
            elif (
                fault_planned and faults_landed() < planned_total
                and len(accepted) < hard_cap
            ):
                submit_one()  # sustain pressure until every planned fault lands
            elif first_id_after_fault is not None and probes_sent < recovery_probes:
                if submit_one():
                    probes_sent += 1
            for ev in self.session.fire("serve.queue_burst", step=cycles):
                for _ in range(int(ev.args.get("count", 8))):
                    submit_one()
            for rid, toks in router.step():
                if rid in streamed:
                    streamed[rid].extend(toks)
            observe_fleet()
            landed = faults_landed()
            if landed > faults_before and first_id_after_fault is None:
                first_id_after_fault = next_id
            faults_before = landed
            cycles += 1
        results = dict(router.drain())
        # Recovery phase: cycle until every ejected replica rejoined (the
        # respawn path), then until the autoscaler converged back to its floor.
        while (
            any(s == "ejected" for s in router.replica_states.values())
            and cycles < max_cycles
        ):
            self.session.clock.sleep(0.01)
            router.step()
            observe_fleet()
            cycles += 1
        for _ in range(router.replica_set.probation_steps + 1):
            router.step()
        while (
            autoscale
            and router.active_replicas > router.min_replicas
            and cycles < max_cycles
        ):
            self.session.clock.sleep(0.01)
            router.step()
            cycles += 1
        observe_fleet()
        final_states = dict(router.replica_states)
        final_active = router.active_replicas
        scale_ups = int(router.stats.get("autoscale", {}).get("scale_ups", 0))
        scale_downs = int(router.stats.get("autoscale", {}).get("scale_downs", 0))
        routing_log = list(router.routing_log)
        state_log = list(router.replica_set.state_log)
        retries_counter = int(router.stats["retries"])
        # Successful reconnects live in the registry (memoized per replica
        # label), so the count survives engine rebuilds mid-sweep.
        reconnects_total = int(sum(
            inst.value for inst in self.session.registry.instruments()
            if inst.name == "router_reconnects_total"
        ))
        router.close()

        journal = self._read_fleet_journal(journal_path)
        finish_reasons = {
            rid: results[rid].finish_reason if rid in results else None for rid in accepted
        }
        non_terminal = {
            rid: reason for rid, reason in finish_reasons.items()
            if reason not in ROUTER_FINISH_REASONS
        }
        duplicate_streams = {
            rid: {"streamed": streamed[rid], "result": list(results[rid].tokens)}
            for rid in accepted
            if rid in results and streamed[rid] != list(results[rid].tokens)
        }
        # `fleet_recovered` must ignore retired replicas: the autoscaler
        # retiring its extra worker after the ramp is convergence, not failure.
        recovery_states = {i: s for i, s in final_states.items() if s != "retired"}
        checks = [
            InvariantCheck(
                "terminal_finish_reasons",
                passed=not non_terminal and not stalled,
                details={
                    "accepted": len(accepted), "rejected_queue_full": rejected,
                    "non_terminal": non_terminal, "stalled": stalled, "cycles": cycles,
                    "reasons": _reason_counts(finish_reasons),
                },
            ),
            InvariantCheck(
                "no_duplicate_streams",
                passed=not duplicate_streams,
                details={"mismatched": duplicate_streams},
            ),
            self._check_fleet_recovered(
                finish_reasons, first_id_after_fault, recovery_states, fault_planned
            ),
            self._check_no_route_to_ejected(routing_log, state_log),
            # A healed partition must not demand a death, so only worker-side
            # fleet faults (or a partition past the reconnect budget) put the
            # warm-restart check into its strict deaths>=1 mode.
            self._check_worker_restart_warm(
                pids_seen, journal, planned_faults > 0 or escalation_expected
            ),
            self._check_fleet_ledger(
                journal, pids_seen, routing_log, retries_counter, accepted,
                finish_reasons, planned_faults,
            ),
        ]
        if net_events:
            checks.append(self._check_reconnect_reconciles(
                reconnects_total, journal, planned_net,
                escalation_expected=escalation_expected,
            ))
            checks.append(self._check_partition_not_death(
                pids_seen, journal, reconnects_total,
                escalation_expected=escalation_expected,
                fleet_planned=planned_faults > 0,
                reconnect_deadline_s=reconnect_deadline_s,
            ))
        if autoscale:
            checks.append(InvariantCheck(
                "autoscaler_converges",
                passed=scale_ups >= 1 and peak_active > router.min_replicas
                and final_active == router.min_replicas and scale_downs >= 1,
                details={
                    "scale_ups": scale_ups, "scale_downs": scale_downs,
                    "peak_active": peak_active, "final_active": final_active,
                    "min_replicas": router.min_replicas,
                    "max_replicas": router.max_replicas,
                },
            ))
        return self._report("fleet", checks)

    @staticmethod
    def _read_fleet_journal(path: str) -> List[dict]:
        if not os.path.exists(path):
            return []
        entries = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail of a SIGKILLed writer
        return entries

    @staticmethod
    def _check_worker_restart_warm(
        pids_seen: Dict[int, List[tuple]],
        journal: List[dict],
        fault_planned: bool,
    ) -> InvariantCheck:
        """Every worker death must be followed by a respawn whose ready
        handshake reports a pre-warmed engine — a restarted worker rejoins the
        fleet WARM, never paying a compile on the serving path."""
        deaths = sum(max(len(v) - 1, 0) for v in pids_seen.values())
        cold_rejoins = {
            index: [pid for pid, warm in seen[1:] if not warm]
            for index, seen in pids_seen.items()
            if any(not warm for _pid, warm in seen[1:])
        }
        if not fault_planned:
            return InvariantCheck(
                "worker_restart_rejoins_warm",
                passed=not cold_rejoins,
                details={"note": "no fleet fault in plan", "deaths": deaths},
            )
        return InvariantCheck(
            "worker_restart_rejoins_warm",
            passed=deaths >= 1 and not cold_rejoins,
            details={
                "observed_deaths": deaths,
                "cold_rejoins": cold_rejoins,
                "pids_per_replica": {
                    i: [pid for pid, _warm in seen] for i, seen in pids_seen.items()
                },
                "journaled_faults": len(journal),
            },
        )

    @staticmethod
    def _check_reconnect_reconciles(
        reconnects_total: int,
        journal: List[dict],
        planned_net: int,
        *,
        escalation_expected: bool = False,
    ) -> InvariantCheck:
        """Controller reconnect counters must reconcile against the workers'
        re-registration journal: every reconnect the controller counted was a
        registration some worker accepted under a bumped epoch (journaled
        worker-side as ``net.reregister`` before the ready frame goes out).
        The journal may run AHEAD of the counter — a handshake that lands but
        tears again during stream reconciliation is journaled by the worker
        yet never counted by the controller — but it can never run behind.
        And unless every planned net fault was an escalation (a window past
        the reconnect budget, where respawn — not reconnect — is the correct
        outcome), at least one reconnect must actually have happened."""
        reregisters = sum(1 for e in journal if e.get("kind") == "net.reregister")
        return InvariantCheck(
            "reconnect_reconciles",
            passed=(
                reregisters >= reconnects_total
                and (reconnects_total >= 1 or escalation_expected)
            ),
            details={
                "controller_reconnects": reconnects_total,
                "journaled_reregisters": reregisters,
                "planned_net_faults": planned_net,
                "escalation_expected": escalation_expected,
            },
        )

    @staticmethod
    def _check_partition_not_death(
        pids_seen: Dict[int, List[tuple]],
        journal: List[dict],
        reconnects_total: int,
        *,
        escalation_expected: bool,
        fleet_planned: bool,
        reconnect_deadline_s: float,
    ) -> InvariantCheck:
        """A healed partition must NOT change any worker's pid: the link
        reconnects and the stream resumes, the process is never respawned.
        Deaths caused by the plan's own worker-side faults (kills, stalls the
        step timeout escalates) are subtracted out; whatever remains is
        attributable to the network — and must be zero unless some partition
        window exceeded ``reconnect_deadline_s``, in which case the budget
        MUST have escalated to at least one respawn."""
        deaths = sum(max(len(v) - 1, 0) for v in pids_seen.values())
        fleet_deaths_budget = sum(
            1 for e in journal
            if e.get("kind") in ("fleet.worker_kill", "fleet.worker_stall")
        )
        net_deaths = deaths if not fleet_planned else max(
            0, deaths - fleet_deaths_budget
        )
        passed = net_deaths >= 1 if escalation_expected else net_deaths == 0
        return InvariantCheck(
            "partition_is_not_death",
            passed=passed,
            details={
                "observed_deaths": deaths,
                "fleet_fault_deaths_budget": fleet_deaths_budget,
                "net_attributed_deaths": net_deaths,
                "escalation_expected": escalation_expected,
                "reconnect_deadline_s": reconnect_deadline_s,
                "controller_reconnects": reconnects_total,
                "pids_per_replica": {
                    i: [pid for pid, _warm in seen] for i, seen in pids_seen.items()
                },
            },
        )

    def _check_fleet_ledger(
        self,
        journal: List[dict],
        pids_seen: Dict[int, List[tuple]],
        routing_log: List[dict],
        retries_counter: int,
        accepted: List[int],
        finish_reasons: Dict[int, Optional[str]],
        planned_faults: int = 0,
    ) -> InvariantCheck:
        """Reconcile three independent records: the controller-side injection
        counters, the worker-side chaos journal (written before each fault
        landed), and the observed process deaths. Every journaled kill must
        correspond to a real death of that worker's process, and the retry
        counter must match the routing journal exactly."""
        counts = self.session.counts()
        registry_ok = all(
            self.session.registry.value("chaos_injected_total", {"kind": kind}) == count
            for kind, count in counts.items()
        )
        journaled_kills: Dict[str, int] = {}
        for entry in journal:
            if entry.get("kind") == "fleet.worker_kill":
                worker = entry.get("worker", "?")
                journaled_kills[worker] = journaled_kills.get(worker, 0) + 1
        deaths_by_worker = {
            f"worker_{index}": max(len(seen) - 1, 0) for index, seen in pids_seen.items()
        }
        kills_unaccounted = {
            worker: count for worker, count in journaled_kills.items()
            if deaths_by_worker.get(worker, 0) < count
        }
        journal_retries = sum(1 for e in routing_log if e["kind"] == "retry")
        finished_total = sum(1 for r in finish_reasons.values() if r is not None)
        # Every PLANNED worker fault must actually have fired (journaled by the
        # worker before its damage): a sweep whose triggers never armed — the
        # workload drained too fast, a path_pattern matched nothing — must go
        # red, not silently pass with unexercised faults.
        fleet_fired = sum(
            1 for e in journal
            if e.get("kind") in ("fleet.worker_kill", "fleet.worker_stall")
        )
        return InvariantCheck(
            "ledger_reconciles",
            passed=registry_ok and not kills_unaccounted
            and journal_retries == retries_counter
            and finished_total == len(accepted)
            and fleet_fired >= planned_faults,
            details={
                "planned_worker_faults": planned_faults,
                "journaled_worker_faults": fleet_fired,
                "controller_injected": counts,
                "registry_matches_journal": registry_ok,
                "worker_journal_kills": journaled_kills,
                "observed_deaths": deaths_by_worker,
                "kills_without_observed_death": kills_unaccounted,
                "router_retries_total": retries_counter,
                "journal_retries": journal_retries,
                "finished_total": finished_total,
                "accepted": len(accepted),
            },
        )

    def _check_fleet_recovered(
        self,
        finish_reasons: Dict[int, Optional[str]],
        first_id_after_fault: Optional[int],
        final_states: Dict[int, str],
        fault_planned: bool,
    ) -> InvariantCheck:
        """After a replica fault, LATER requests must complete normally (the
        fleet degraded instead of failing) and no replica may end the run
        ejected — the cooldown/rejoin machinery must have brought it back."""
        if not fault_planned:
            return InvariantCheck(
                "fleet_recovered", True, {"note": "no router fault in plan"}
            )
        later = {
            rid: fr for rid, fr in finish_reasons.items()
            if first_id_after_fault is not None and rid >= first_id_after_fault
        }
        bad = {
            rid: fr for rid, fr in later.items()
            if fr not in ("eos", "length", "timeout")
        }
        still_ejected = {i: s for i, s in final_states.items() if s == "ejected"}
        return InvariantCheck(
            "fleet_recovered",
            passed=bool(later) and not bad and not still_ejected,
            details={
                "requests_after_fault": len(later),
                "abnormal_after_fault": bad,
                "final_replica_states": final_states,
                "first_id_after_fault": first_id_after_fault,
            },
        )

    @staticmethod
    def _check_no_route_to_ejected(
        routing_log: List[dict], state_log: List[dict]
    ) -> InvariantCheck:
        """Audit every routing decision against the health history: the router
        journals the replica's state at decision time, and the state log lets
        us independently reconstruct ejected/draining windows."""
        bad = [e for e in routing_log if e.get("state") in ("ejected", "draining", "retired")]
        # Independent reconstruction: walk the state log and verify no routing
        # timestamp lands inside an (ejected -> rejoining) window.
        windows: Dict[int, List[List[float]]] = {}
        for tr in state_log:
            if tr["to"] == "ejected":
                windows.setdefault(tr["replica"], []).append([tr["t"], float("inf")])
            elif tr["from"] == "ejected" and tr["replica"] in windows:
                spans = windows[tr["replica"]]
                if spans and spans[-1][1] == float("inf"):
                    spans[-1][1] = tr["t"]
        inside = [
            e for e in routing_log
            if any(
                lo < e["t"] < hi
                for lo, hi in windows.get(e["replica"], [])
            )
        ]
        return InvariantCheck(
            "no_route_to_ejected",
            passed=not bad and not inside,
            details={
                "decisions": len(routing_log),
                "routed_while_unroutable": bad,
                "routed_inside_ejected_window": inside,
                "ejection_windows": {k: v for k, v in windows.items()},
            },
        )

    def _check_router_ledger(
        self,
        routing_log: List[dict],
        retries_counter: int,
        accepted: List[int],
        finish_reasons: Dict[int, Optional[str]],
    ) -> InvariantCheck:
        counts = self.session.counts()
        registry_ok = all(
            self.session.registry.value("chaos_injected_total", {"kind": kind}) == count
            for kind, count in counts.items()
        )
        journal_retries = sum(1 for e in routing_log if e["kind"] == "retry")
        finished_total = sum(1 for r in finish_reasons.values() if r is not None)
        return InvariantCheck(
            "ledger_reconciles",
            passed=registry_ok and journal_retries == retries_counter
            and finished_total == len(accepted),
            details={
                "injected_counts": counts,
                "registry_matches_journal": registry_ok,
                "router_retries_total": retries_counter,
                "journal_retries": journal_retries,
                "finished_total": finished_total,
                "accepted": len(accepted),
            },
        )

    @staticmethod
    def _check_page_ledger(engine) -> InvariantCheck:
        """Paged engines must end a drained run with ZERO pages in use — every
        refcount returned through finish/cancel/error/abort, none leaked by the
        blast-radius rebuild — and a structurally consistent pool: no page both
        free and cached, no prefix registration pointing at a freed page (the
        'resurrected prefix' failure a post-recovery stale hash map would
        cause). Contiguous engines pass vacuously."""
        pool = getattr(engine, "pool", None)
        if pool is None:
            return InvariantCheck("page_ledger", True, {"note": "contiguous engine (no pool)"})
        problems = pool.check_consistency()
        return InvariantCheck(
            "page_ledger",
            passed=pool.pages_in_use == 0 and not problems,
            details={
                "pages_in_use_after_drain": pool.pages_in_use,
                "consistency_problems": problems,
                **pool.stats(),
            },
        )

    def _check_engine_recovered(
        self, finish_reasons: Dict[int, Optional[str]], first_id_after_error: Optional[int]
    ) -> InvariantCheck:
        """After a dispatch failure's blast radius, requests submitted LATER
        must still complete normally — the engine degrades per-step, never
        permanently."""
        if first_id_after_error is None:
            return InvariantCheck(
                "engine_recovered", True, {"note": "no dispatch_error fault in plan"}
            )
        later = {r: fr for r, fr in finish_reasons.items() if r >= first_id_after_error}
        bad = {r: fr for r, fr in later.items() if fr == "error"}
        return InvariantCheck(
            "engine_recovered",
            passed=bool(later) and not bad,
            details={
                "requests_after_error": len(later),
                "errored_after_recovery": bad,
                "first_id_after_error": first_id_after_error,
            },
        )

    def _check_serve_ledger(self, engine, accepted: List[int]) -> InvariantCheck:
        counts = self.session.counts()
        registry_ok = all(
            self.session.registry.value("chaos_injected_total", {"kind": kind}) == count
            for kind, count in counts.items()
        )
        finished_total = sum(engine.stats["finish_reasons"].values())
        return InvariantCheck(
            "ledger_reconciles",
            passed=registry_ok and finished_total == len(accepted),
            details={
                "injected_counts": counts,
                "registry_matches_journal": registry_ok,
                "finished_total": finished_total,
                "accepted": len(accepted),
            },
        )

    # ---------------------------------------------------------------- trace checks
    def _trace_records(self) -> List[dict]:
        """Everything THIS run traced: the streamed files when a trace dir is
        armed (they carry every process, including SIGKILLed children whose
        ring died with them), else the in-memory ring. Dir records are
        filtered to this run's trace id — the dir may legitimately hold other
        tracers' spans (a prior run reusing the dir, the workload
        Accelerator's own default tracer armed off ACCELERATE_TPU_TRACE_DIR
        with a different id) and foreign spans must not fail the invariant."""
        if self.trace_dir:
            return [
                r for r in collect_trace_dir(self.trace_dir)
                if r.get("trace_id") == self.tracer.trace_id
            ]
        return self.tracer.recorder.records()

    def _check_trace_complete(
        self, journal: Dict[str, Any], supervised: bool = False
    ) -> InvariantCheck:
        """The stitched timeline must be a complete account of the sweep:
        every journaled injection appears as a `chaos.*` event (reconciling
        with `chaos_injected_total`), a kill that fired left a crash boundary,
        a restart that happened shows up as a post-boundary attempt, every
        span parents into the timeline (no orphans), and the whole sweep
        shares ONE trace id across processes."""
        kill_kinds = {"proc.sigkill", "proc.sigterm", "fs.crash_in_rename"}
        records = self._trace_records()
        if supervised and not self.trace_dir:
            return InvariantCheck(
                "trace_complete", True,
                {"note": "no trace_dir armed; child spans were not durable"},
            )
        details: Dict[str, Any] = {"records": len(records)}
        problems: List[str] = []

        spans = [r for r in records if r.get("kind") in ("span", "span_start")]
        events = [r for r in records if r.get("kind") == "event"]
        known_ids = {r.get("span_id") for r in spans}
        orphans = [
            r.get("name") for r in spans
            if r.get("parent_id") is not None and r.get("parent_id") not in known_ids
        ]
        if orphans:
            problems.append(f"orphan spans (parent id unresolved): {sorted(set(orphans))}")
        # _trace_records already scopes to this run's trace id; the check here
        # is that the run's own processes all STITCHED onto it (a worker that
        # failed to inherit the id would simply be missing from `records`).
        details["trace_id"] = self.tracer.trace_id

        injection_events = [
            e for e in events
            if e["name"].startswith("chaos.") and e["name"] != "chaos.crash_boundary"
        ]
        injected = len(self.session.injections)
        counter_total = sum(
            m.get("value", 0) for m in self.session.registry.snapshot()
            if m["name"] == "chaos_injected_total"
        )
        details["injections_journaled"] = injected
        details["injection_events"] = len(injection_events)
        details["chaos_injected_total"] = counter_total
        if len(injection_events) != injected or counter_total != injected:
            problems.append("injection events do not reconcile with the journal/counters")

        fired_kills = [e for e in self.session.injections if e["kind"] in kill_kinds]
        details["kill_injections"] = len(fired_kills)
        if fired_kills:
            boundaries = [e["t_unix"] for e in events if e["name"] == "chaos.crash_boundary"]
            boundaries += [
                e["t_unix"] for e in events
                if e["name"] == "supervisor.child_exit" and e["attrs"].get("exit_code") != 0
            ]
            details["crash_boundaries"] = len(boundaries)
            if not boundaries:
                problems.append("kill injections fired but no crash boundary was traced")
            elif journal["attempts"] > 1:
                first = min(boundaries)
                resumed = [
                    r for r in spans
                    if r.get("name") == "train.attempt" and r.get("start_unix", 0) > first
                ] + [e for e in events if e["name"] == "train.resume" and e["t_unix"] > first]
                details["post_crash_attempts"] = len(resumed)
                if not resumed:
                    problems.append(
                        "restarts happened but no attempt/resume appears after the "
                        "first crash boundary"
                    )
        details["problems"] = problems
        return InvariantCheck("trace_complete", passed=not problems, details=details)

    def _check_serve_trace(self, accepted: List[int]) -> InvariantCheck:
        """Serving half of trace completeness: every ACCEPTED request left a
        `serve.request` span carrying a terminal finish_reason (submit ->
        finish is fully covered even through blast-radius recoveries), and
        injected serve faults appear as `chaos.serve.*` events."""
        from ..serving import FINISH_REASONS

        records = self._trace_records()
        request_spans = {
            r["attrs"].get("request_id"): r
            for r in records
            if r.get("kind") == "span" and r.get("name") == "serve.request"
        }
        missing = [rid for rid in accepted if rid not in request_spans]
        non_terminal = {
            rid: request_spans[rid]["attrs"].get("finish_reason")
            for rid in accepted
            if rid in request_spans
            and request_spans[rid]["attrs"].get("finish_reason") not in FINISH_REASONS
        }
        injection_events = sum(
            1 for r in records
            if r.get("kind") == "event" and r["name"].startswith("chaos.serve.")
        )
        serve_injected = sum(
            1 for e in self.session.injections if e["kind"].startswith("serve.")
        )
        return InvariantCheck(
            "trace_complete",
            passed=not missing and not non_terminal and injection_events == serve_injected,
            details={
                "accepted": len(accepted),
                "request_spans": len(request_spans),
                "missing_spans": missing,
                "non_terminal_spans": non_terminal,
                "serve_injections": serve_injected,
                "serve_injection_events": injection_events,
            },
        )

    # ---------------------------------------------------------------- shared checks
    @staticmethod
    def _check_resume_exactness(journal: Dict[str, Any]) -> InvariantCheck:
        failures = []
        known = {}
        for entry in journal["intents"]:
            known.setdefault(entry["step"], set()).add(entry["digest"])
        for entry in journal["saves"]:
            known.setdefault(entry["step"], set()).add(entry["digest"])
        for resume in journal["resumes"]:
            step, digest = resume.get("step"), resume.get("digest")
            if step is None:
                failures.append({"resume": resume, "why": "resolved checkpoint has no step"})
            elif step not in known:
                failures.append({"resume": resume, "why": f"no committed save for step {step}"})
            elif digest not in known[step]:
                failures.append({"resume": resume, "why": "restored params != committed digest"})
        return InvariantCheck(
            "resume_exactness",
            passed=not failures,
            details={"resumes": len(journal["resumes"]), "failures": failures},
        )

    @staticmethod
    def _check_no_torn_resolved(journal: Dict[str, Any], checkpoint_base: str) -> InvariantCheck:
        failures = []
        for resume in journal["resumes"]:
            if not resume.get("independently_verified"):
                failures.append({"resume": resume, "why": "resolved checkpoint fails digests"})
            elif resume.get("expected_step") is not None and resume.get("step") != resume.get(
                "expected_step"
            ):
                failures.append({
                    "resume": resume,
                    "why": "resolve() skipped or overshot the newest verified checkpoint",
                })
        # Terminal state: whatever 'latest' would resolve to now must verify.
        final_latest = independent_latest_step(checkpoint_base)
        return InvariantCheck(
            "no_torn_resolved",
            passed=not failures,
            details={
                "resumes": len(journal["resumes"]),
                "failures": failures,
                "final_verified_latest_step": final_latest,
            },
        )

    @staticmethod
    def _check_zero_state_sharded(journal: Dict[str, Any]) -> InvariantCheck:
        """2D-mesh workloads only: every attempt journals its optimizer-state
        layout after prepare (``layout`` records) and after every restore
        (``zero_state_sharded`` on resume records) — ALL of them must report
        the moments live-sharded along "data". A restart that silently
        replicates the state trains the same numbers while spending data_n x
        the HBM, which is exactly the failure mode a byte-layout invariant
        exists to catch."""
        records = [
            {"kind": "layout", **e} for e in journal.get("layouts", [])
        ] + [
            {"kind": "resume", "step": e.get("step"),
             "zero_state_sharded": e.get("zero_state_sharded")}
            for e in journal.get("resumes", [])
        ]
        failures = [r for r in records if r.get("zero_state_sharded") is not True]
        return InvariantCheck(
            "zero_state_sharded",
            passed=bool(records) and not failures,
            details={"records": len(records), "failures": failures},
        )

    @staticmethod
    def _check_restart_budget(
        completed: bool, restarts: int, max_restarts: int, downtime_s: float,
        downtime_budget_s: float,
    ) -> InvariantCheck:
        return InvariantCheck(
            "restart_budget",
            passed=completed and restarts <= max_restarts and downtime_s <= downtime_budget_s,
            details={
                "completed": completed,
                "restarts": restarts,
                "max_restarts": max_restarts,
                "downtime_s": round(downtime_s, 6),
                "downtime_budget_s": downtime_budget_s,
            },
        )

    def _check_ledger_reconciles(
        self, ledger: Dict[str, float], journal: Dict[str, Any], async_save: bool = False
    ) -> InvariantCheck:
        counts = self.session.counts()
        registry_ok = all(
            self.session.registry.value("chaos_injected_total", {"kind": kind}) == count
            for kind, count in counts.items()
        )
        fired = self.session.event_fire_counts()
        injected_fsync_s = sum(
            float(ev.args.get("delay_s", 0.05)) * fired[i]
            for i, ev in enumerate(self.plan.events)
            if ev.kind == "fs.slow_fsync"
        )
        if async_save:
            # Async saves: an injected stall runs on the background committer,
            # so its time must land in checkpoint_async_commit_seconds (folded
            # into the ledger as "checkpoint_async_commit") and/or in the
            # blocking barrier charge when the next save caught the commit in
            # flight — never vanish. An ABORTED commit (killed mid-stall)
            # legitimately truncates its recording, so the sweep-stable
            # assertion is existence, not magnitude: stalls injected => commit
            # and/or blocking time was accounted. The exact only-blocking-time
            # split is pinned by the deterministic goodput property test.
            accounted = ledger.get("checkpoint", 0.0) + ledger.get("checkpoint_async_commit", 0.0)
            checkpoint_ok = injected_fsync_s == 0.0 or accounted > 0.0
        else:
            # Injected fsync stalls happen inside save_state, so the goodput
            # ledger's "checkpoint" cause must carry at least that much (10%
            # scheduling tolerance); every resume charges "restart".
            checkpoint_ok = ledger.get("checkpoint", 0.0) >= 0.9 * injected_fsync_s
        restart_ok = (not journal["resumes"]) or ledger.get("restart", 0.0) > 0.0
        return InvariantCheck(
            "ledger_reconciles",
            passed=registry_ok and checkpoint_ok and restart_ok,
            details={
                "injected_counts": counts,
                "registry_matches_journal": registry_ok,
                "goodput_ledger_s": {k: round(v, 6) for k, v in sorted(ledger.items())},
                "injected_fsync_s": round(injected_fsync_s, 6),
                "async_save": async_save,
            },
        )

    # ---------------------------------------------------------------- report assembly
    def _report(
        self,
        workload: str,
        checks: List[InvariantCheck],
        diagnostics: Optional[List[dict]] = None,
    ) -> InvariantReport:
        return InvariantReport(
            plan=self.plan.to_dict(),
            workload=workload,
            checks=checks,
            injections=list(self.session.injections),
            metrics=self.session.registry.snapshot(),
            diagnostics=list(diagnostics or []),
        )

"""Composable fault injectors: the seams where scripted faults enter the stack.

Each injector arms one seam the production code already owns:

  - `FilesystemInjector` — the chaos hooks inside `checkpointing.atomic_write`
    (write / fsync / rename-window) and `CheckpointManager._publish`
    (directory rename + post-publish corruption), so torn writes, ENOSPC/EIO,
    slow fsyncs and rename-window crashes land exactly where real storage
    faults do.
  - `StepBoundaryInjector` — polled at training step boundaries (the chaos
    analogue of `ProfilerManager.poll()`): SIGKILL/SIGTERM delivery and forced
    retraces.
  - `ServingInjector` — wraps a `ContinuousBatcher`'s compiled-program
    dispatches (decode chunk + per-bucket inserts) for stalls and failures;
    queue bursts are driven by the runner.
  - `HarnessInjector` — the seeded-regression fixture: neuters checkpoint
    digest verification so the invariant checker (which verifies
    independently) must go red.

Every firing is counted in ``chaos_injected_total{kind=...}`` on the session's
`MetricsRegistry` and journaled on `ChaosSession.injections`, so invariant
reports can cross-check what was injected against what the goodput ledger and
the serving counters recorded.
"""

from __future__ import annotations

import errno as _errno
import fnmatch
import os
import signal as _signal
import threading
import time
from typing import Any, Dict, List, Optional

from ..logging import get_logger
from ..telemetry import MetricsRegistry
from .plan import FAULT_KINDS, FaultEvent, FaultPlan

logger = get_logger(__name__)

_ERRNO_BY_NAME = {"ENOSPC": _errno.ENOSPC, "EIO": _errno.EIO}


class InjectedKill(BaseException):
    """The in-process SIGKILL analogue. Deliberately NOT an `Exception`: a hard
    kill gives no handler a chance to clean up, so catch-all `except Exception`
    blocks in the code under test must not swallow it either."""


class InjectedBackendError(RuntimeError):
    """A scripted backend/dispatch failure (the injected stand-in for a device
    error during a compiled-program call)."""


class FakeClock:
    """Deterministic virtual clock for backoff/deadline tests: `sleep()`
    advances the clock instead of blocking, so schedules spanning simulated
    hours run in microseconds while every deadline comparison sees the full
    wait."""

    def __init__(self, start: float = 1_000_000.0):
        self.t = float(start)
        self.start = self.t
        self.sleeps: List[float] = []

    def time(self) -> float:
        return self.t

    def monotonic(self) -> float:
        return self.t

    def perf_counter(self) -> float:
        return self.t

    def sleep(self, seconds: float):
        self.sleeps.append(float(seconds))
        self.t += float(seconds)

    def elapsed(self) -> float:
        return self.t - self.start


class _RealClock:
    monotonic = staticmethod(time.monotonic)
    perf_counter = staticmethod(time.perf_counter)
    sleep = staticmethod(time.sleep)
    time = staticmethod(time.time)


class ChaosSession:
    """Shared state for one chaos run: the plan, per-event trigger counters,
    the injection journal, and the metrics registry the counters publish to.

    `fire(kind, step=..., path=...)` is the single trigger evaluator every
    injector calls at its seam: it returns the events that fire *now* (already
    recorded/counted), so an injector's job reduces to "for each fired event,
    do the damage"."""

    def __init__(
        self,
        plan: FaultPlan,
        registry: Optional[MetricsRegistry] = None,
        clock=None,
        tracer=None,
    ):
        self.plan = plan
        self.registry = registry if registry is not None else MetricsRegistry()
        self.clock = clock if clock is not None else _RealClock()
        #: Optional telemetry tracer: every injection additionally lands as a
        #: `chaos.<kind>` trace event (recorded — and, with a trace dir,
        #: streamed — BEFORE the fault's damage executes, like `on_inject`),
        #: so fault sweeps produce readable timelines and the runner's
        #: trace_complete invariant can reconcile events against counters.
        self.tracer = tracer
        self._lock = threading.Lock()
        self._armed_at = self.clock.monotonic()
        self._state = [{"calls": 0, "fired": 0} for _ in plan.events]
        #: Journal of every injected fault: {"kind", "t_s", "step", "path"}.
        self.injections: List[Dict[str, Any]] = []
        #: Optional sink called with each injection record the moment it is
        #: journaled — subprocess workloads persist records through this BEFORE
        #: the fault lands (a SIGKILL firing right after must not erase the
        #: evidence that it fired).
        self.on_inject = None

    def elapsed_s(self) -> float:
        return self.clock.monotonic() - self._armed_at

    def counts(self) -> Dict[str, int]:
        """Injected-fault totals by kind (mirrors `chaos_injected_total`)."""
        out: Dict[str, int] = {}
        for entry in self.injections:
            out[entry["kind"]] = out.get(entry["kind"], 0) + 1
        return out

    def preconsume(self, kind: str, count: int, path: Optional[str] = None):
        """Mark `count` prior firings of `kind` (matching `path` when the event
        is path-targeted) as already consumed — WITHOUT journaling or counting
        them again. The restart half of a per-process env-propagated plan: a
        respawned worker re-arms the same plan, reads its own past firings back
        from the shared journal, and pre-consumes them so a `times`-bounded
        kill cannot re-fire forever (the PR 9 at_step-SIGKILL livelock, closed
        at the session layer). Events with ``times=0`` (unlimited) cannot be
        pre-consumed past their cap — they have none."""
        with self._lock:
            remaining = int(count)
            for i, ev in enumerate(self.plan.events):
                if remaining <= 0:
                    break
                if ev.kind != kind:
                    continue
                if ev.path_pattern is not None and (
                    path is None or not _path_matches(path, ev.path_pattern)
                ):
                    continue
                state = self._state[i]
                take = remaining if ev.times == 0 else min(
                    remaining, max(ev.times - state["fired"], 0)
                )
                state["fired"] += take
                # at_call is an EXACT call-count match: advancing `calls` to it
                # would disarm the trigger forever. Only park the counter past
                # the trigger once the event's budget is fully consumed — an
                # event with firings left (times > fired, or times=0 unlimited)
                # must keep counting fresh calls in the new process so its
                # remaining firings can still trigger.
                if (
                    ev.at_call is not None
                    and take
                    and ev.times
                    and state["fired"] >= ev.times
                ):
                    state["calls"] = max(state["calls"], ev.at_call)
                remaining -= take

    def event_fire_counts(self) -> List[int]:
        """Per-event fired totals, aligned with `plan.events` (how invariant
        checks attribute injected delays to the specific event that caused
        them)."""
        with self._lock:
            return [state["fired"] for state in self._state]

    def fire(
        self,
        kind: str,
        step: Optional[int] = None,
        path: Optional[str] = None,
        require_pattern: bool = False,
    ) -> List[FaultEvent]:
        """Evaluate every event of `kind` against this call site's context.
        A trigger field an event sets must match; a field it leaves unset never
        constrains — EXCEPT that a path-triggered event only fires at path
        sites, a step-triggered event only at step sites, and a site passing
        `require_pattern` (the secondary seam of a multi-seam kind, e.g.
        `proc.sigterm`'s artifact-write site) only fires events that opted in
        with a `path_pattern`. Together the sites stay disjoint: one event is
        only ever evaluated — and its call counter only ever advanced — at one
        seam."""
        fired: List[FaultEvent] = []
        with self._lock:
            for i, ev in enumerate(self.plan.events):
                if ev.kind != kind:
                    continue
                if require_pattern and ev.path_pattern is None:
                    continue
                if ev.path_pattern is not None and (
                    path is None or not _path_matches(path, ev.path_pattern)
                ):
                    continue
                if ev.at_step is not None and step != ev.at_step:
                    continue
                if ev.after_s is not None and self.elapsed_s() < ev.after_s:
                    continue
                state = self._state[i]
                state["calls"] += 1
                if ev.at_call is not None and state["calls"] != ev.at_call:
                    continue
                if ev.times and state["fired"] >= ev.times:
                    continue
                state["fired"] += 1
                self._record_locked(ev, step=step, path=path)
                fired.append(ev)
        if fired:
            for entry in self.injections[-len(fired):]:
                if self.tracer is not None:
                    self.tracer.event(
                        f"chaos.{entry['kind']}", category="chaos",
                        step=entry.get("step"), path=entry.get("path"),
                        t_s=entry["t_s"],
                    )
                if self.on_inject is not None:
                    self.on_inject(dict(entry))
        return fired

    def _record_locked(self, event: FaultEvent, step: Optional[int], path: Optional[str]):
        entry: Dict[str, Any] = {"kind": event.kind, "t_s": round(self.elapsed_s(), 6)}
        if step is not None:
            entry["step"] = step
        if path is not None:
            entry["path"] = os.path.basename(path)
        self.injections.append(entry)
        self.registry.counter(
            "chaos_injected_total",
            help="faults injected by the chaos subsystem, by kind",
            labels={"kind": event.kind},
        ).inc()
        logger.info("chaos: injected %s (step=%s path=%s)", event.kind, step, entry.get("path"))


def _path_matches(path: str, pattern: str) -> bool:
    """Match the basename (the common case: 'model.npz*', 'MANIFEST.json') or,
    for patterns with separators, the full path."""
    if fnmatch.fnmatch(os.path.basename(path), pattern):
        return True
    return os.sep in pattern and fnmatch.fnmatch(path, pattern)


# ------------------------------------------------------------------ filesystem
class FilesystemInjector:
    """Arms the chaos seam in `checkpointing` (`_chaos_hooks`): a context
    manager so a crashed run can never leave faults armed for the next test."""

    def __init__(self, session: ChaosSession):
        self.session = session

    def __enter__(self) -> "FilesystemInjector":
        from .. import checkpointing

        if checkpointing._chaos_hooks is not None:
            raise RuntimeError("another FilesystemInjector is already armed")
        checkpointing._chaos_hooks = self
        return self

    def __exit__(self, *exc):
        from .. import checkpointing

        checkpointing._chaos_hooks = None
        return False

    # ---- seam callbacks (called by checkpointing when armed) ----
    def on_write(self, path: str):
        """Entry of `atomic_write(path, ...)` — before any byte lands."""
        # proc.sigterm's PRIMARY seam is the step boundary; only events that
        # opted in with a path_pattern fire here (mid-commit delivery).
        for ev in self.session.fire("proc.sigterm", path=path, require_pattern=True):
            os.kill(os.getpid(), _signal.SIGTERM)
        for ev in self.session.fire("fs.io_error", path=path):
            code = _ERRNO_BY_NAME.get(str(ev.args.get("errno", "EIO")).upper(), _errno.EIO)
            raise OSError(code, os.strerror(code), path)

    def on_fsync(self, path: str):
        """Just before the payload fsync."""
        for ev in self.session.fire("fs.slow_fsync", path=path):
            self.session.clock.sleep(float(ev.args.get("delay_s", 0.05)))

    def on_rename(self, path: str):
        """Inside the rename window: payload fsynced, `os.replace` not yet run."""
        for ev in self.session.fire("fs.crash_in_rename", path=path):
            raise InjectedKill(f"chaos: killed in rename window of {os.path.basename(path)}")

    def on_publish_rename(self, staging: str, final: str):
        """Before `CheckpointManager._publish`'s directory rename (transient
        publish I/O errors land here, and so does the publish-window kill: the
        staged checkpoint — manifest included — is fully on disk, the rename
        has not run, so a death here must leave the PREVIOUS published
        checkpoint as the resolvable latest. The async-commit sweeps aim this
        at the background committer thread)."""
        for ev in self.session.fire("fs.crash_in_rename", path=final):
            raise InjectedKill(
                f"chaos: killed in publish-rename window of {os.path.basename(final)}"
            )
        for ev in self.session.fire("fs.io_error", path=final):
            code = _ERRNO_BY_NAME.get(str(ev.args.get("errno", "EIO")).upper(), _errno.EIO)
            raise OSError(code, os.strerror(code), final)

    def on_published(self, final: str):
        """After a checkpoint directory (and its latest pointer) committed:
        post-commit corruption — the torn-persistence / bit-rot model."""
        for root, dirs, names in os.walk(final):
            for name in names:
                full = os.path.join(root, name)
                for ev in self.session.fire("fs.torn_write", path=full):
                    _tear_file(full, ev.args)


def _tear_file(path: str, args: Dict[str, Any]):
    """Corrupt a committed file: truncate at a byte offset (or fraction of its
    size), or flip one byte in place when args.flip is set."""
    size = os.path.getsize(path)
    if "offset_frac" in args:
        offset = int(size * float(args["offset_frac"]))
    else:
        offset = int(args.get("offset", size // 2))
    offset = max(0, min(offset, max(size - 1, 0)))
    with open(path, "r+b") as f:
        if args.get("flip"):
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([(byte[0] ^ 0xFF) if byte else 0xFF]))
        else:
            f.truncate(offset)


# ------------------------------------------------------------------ process / backend
class StepBoundaryInjector:
    """Polled at step boundaries (`poll(step)`), like the profiler's capture
    poll. `hard=True` delivers real signals (subprocess workloads); the
    in-process default raises `InjectedKill` for SIGKILL so the supervised-loop
    harness can observe the death without losing the interpreter."""

    def __init__(self, session: ChaosSession, hard: bool = False):
        self.session = session
        self.hard = hard

    def poll(self, step: int):
        for _ev in self.session.fire("backend.recompile", step=step):
            import jax

            jax.clear_caches()
        for _ev in self.session.fire("proc.sigterm", step=step):
            os.kill(os.getpid(), _signal.SIGTERM)
        for _ev in self.session.fire("proc.sigkill", step=step):
            if self.hard:
                os.kill(os.getpid(), _signal.SIGKILL)
                time.sleep(5)  # unreachable — SIGKILL is unmaskable; belt for exotic platforms
            raise InjectedKill(f"chaos: SIGKILL at step boundary {step}")


# ------------------------------------------------------------------ serving
class ServingInjector:
    """Wraps a `ContinuousBatcher`'s compiled-program dispatches. Stalls and
    failures fire by call count / wall clock (`at_call` counts decode-chunk
    dispatches for `serve.dispatch_*` and insert dispatches for
    `serve.insert_error`). Queue bursts are a workload-level fault the
    `ChaosRunner` serve loop drives."""

    def __init__(self, session: ChaosSession):
        self.session = session

    def arm(self, engine) -> "ServingInjector":
        session = self.session
        real_chunk = engine._chunk_fn

        def chunk_with_chaos(*args, **kwargs):
            for ev in session.fire("serve.dispatch_stall"):
                session.clock.sleep(float(ev.args.get("delay_s", 0.05)))
            for ev in session.fire("serve.dispatch_error"):
                if ev.args.get("consume_donated"):
                    _consume_donated_state(engine)
                raise InjectedBackendError("chaos: decode-chunk dispatch failed")
            return real_chunk(*args, **kwargs)

        engine._chunk_fn = chunk_with_chaos
        real_insert_fn = engine._insert_fn

        def insert_fn_with_chaos(bucket):
            fn = real_insert_fn(bucket)

            def wrapped(*args, **kwargs):
                for ev in session.fire("serve.insert_error"):
                    if ev.args.get("consume_donated"):
                        _consume_donated_state(engine)
                    raise InjectedBackendError("chaos: insert dispatch failed")
                return fn(*args, **kwargs)

            return wrapped

        engine._insert_fn = insert_fn_with_chaos
        return self


class RouterInjector:
    """Per-replica fault seams on a serving `Router`'s fleet: wraps EVERY
    replica engine's decode-chunk dispatch, identifying the replica through the
    `path` trigger channel (``path_pattern: "replica_0"`` targets replica 0;
    `at_call` then counts that replica's own dispatches). Re-arms automatically
    when the `ReplicaSet` rebuilds a killed replica's engine, so a rejoined
    replica is chaos-visible again.

      - ``router.replica_stall``  — sleep before the dispatch (degraded signal)
      - ``router.replica_poison`` — raise `InjectedBackendError` (engine blast
        radius; the replica survives, the router's failure counter observes it)
      - ``router.replica_kill``   — raise `InjectedKill` (a BaseException the
        engine's fault isolation must NOT swallow: the in-process analogue of
        a worker process SIGKILL — the router must eject and recover)
    """

    def __init__(self, session: ChaosSession):
        self.session = session

    def arm(self, router) -> "RouterInjector":
        session = self.session

        def wrap(index, engine):
            real_chunk = engine._chunk_fn
            token = f"replica_{index}"

            def chunk_with_chaos(*args, **kwargs):
                for ev in session.fire("router.replica_stall", path=token):
                    session.clock.sleep(float(ev.args.get("delay_s", 0.05)))
                for ev in session.fire("router.replica_poison", path=token):
                    raise InjectedBackendError(
                        f"chaos: poisoned decode dispatch on replica {index}"
                    )
                for ev in session.fire("router.replica_kill", path=token):
                    raise InjectedKill(f"chaos: killed replica {index}")
                return real_chunk(*args, **kwargs)

            engine._chunk_fn = chunk_with_chaos

        for replica in router.replica_set.replicas:
            wrap(replica.index, replica.engine)
        router.replica_set.on_engine_built.append(wrap)
        return self


class _ChaosTransport:
    """Wraps one subprocess engine's frame transport so network faults land at
    the exact seam real ones do — between the proxy and the socket. While a
    partition window is open every frame (both directions) raises `WorkerGone`
    and `reconnect` refuses with `ConnectionError`; when the window heals, the
    next reconnect goes through to the real transport's re-handshake. The
    wrapped transport keeps its full surface (pid/alive/kill/close/sever pass
    through), so the engine proxy cannot tell chaos from a real flaky link."""

    def __init__(self, inner, session: ChaosSession, token: str):
        self._inner = inner
        self._session = session
        self._token = token
        self._down_until = 0.0

    def _now(self) -> float:
        return self._session.clock.monotonic()

    def _check_down(self, op):
        if self._now() < self._down_until:
            from ..worker import WorkerGone

            raise WorkerGone(
                f"chaos: link to {self._token} is partitioned "
                f"[peer={self._token} op={op}]"
            )

    def _open_partition(self, window_s: float):
        self._down_until = max(self._down_until, self._now() + float(window_s))
        sever = getattr(self._inner, "sever", None)
        if sever is not None:
            sever()

    def send(self, obj):
        from ..worker import FrameTimeout, WorkerGone

        op = obj.get("op") if isinstance(obj, dict) else None
        self._check_down(op)
        fired = False
        for ev in self._session.fire("net.partition", path=self._token):
            self._open_partition(ev.args.get("window_s", 0.5))
            fired = True
        for ev in self._session.fire("net.flap", path=self._token):
            self._open_partition(ev.args.get("window_s", 0.1))
            fired = True
        if fired:
            raise WorkerGone(
                f"chaos: partitioned link to {self._token} "
                f"[peer={self._token} op={op}]"
            )
        for _ev in self._session.fire("net.slow", path=self._token):
            raise FrameTimeout(
                f"chaos: injected latency pushed the frame past its deadline "
                f"[peer={self._token} op={op}]"
            )
        return self._inner.send(obj)

    def recv(self, timeout_s):
        self._check_down(None)
        return self._inner.recv(timeout_s=timeout_s)

    def reconnect(self, timeout_s):
        if self._now() < self._down_until:
            raise ConnectionError(
                f"chaos: link to {self._token} is still partitioned "
                f"({self._down_until - self._now():.3f}s left in the window)"
            )
        return self._inner.reconnect(timeout_s=timeout_s)

    def sever(self):
        sever = getattr(self._inner, "sever", None)
        if sever is not None:
            sever()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TransportInjector:
    """Network chaos on a socket fleet: wraps every subprocess replica's
    transport in `_ChaosTransport`, identifying the worker through the `path`
    trigger channel (``path_pattern: "worker_0"``; `at_call` counts that
    worker's frame sends). Re-arms through `on_engine_built` so a respawned
    worker's fresh transport is chaos-visible again.

      - ``net.partition`` — sever the link for args.window_s (reconnect must
        heal it; only a window past the engine's reconnect_deadline_s may
        escalate to respawn)
      - ``net.slow``      — a frame send raises FrameTimeout (latency past the
        deadline: the slow-network face of the same transport fault)
      - ``net.flap``      — repeated short partitions (times=N for N flaps)
    """

    def __init__(self, session: ChaosSession):
        self.session = session

    def arm(self, router) -> "TransportInjector":
        session = self.session

        def wrap(index, engine):
            transport = getattr(engine, "transport", None)
            if transport is None or isinstance(transport, _ChaosTransport):
                return
            engine.transport = _ChaosTransport(transport, session, f"worker_{index}")

        for replica in router.replica_set.replicas:
            wrap(replica.index, replica.engine)
        router.replica_set.on_engine_built.append(wrap)
        return self


def _consume_donated_state(engine):
    """Model the accelerator-only half of a dispatch failure: a program that
    started executing CONSUMES its donated operands even when it fails, leaving
    the engine's cache (and presence) referencing deleted buffers. CPU ignores
    donation, so without this explicit `delete()` the poisoning the engine's
    rebuild path guards against could never be exercised in tier-1 — the
    regression pin would be vacuous."""
    import jax

    for leaf in jax.tree_util.tree_leaves(engine._cache):
        if hasattr(leaf, "delete"):
            leaf.delete()
    if engine._presence is not None:
        for leaf in jax.tree_util.tree_leaves(engine._presence):
            if hasattr(leaf, "delete"):
                leaf.delete()


# ------------------------------------------------------------------ harness regression
class HarnessInjector:
    """`harness.disable_verification`: patch `checkpointing.verify_checkpoint_dir`
    to vacuous truth — the scripted stand-in for a broken digest layer. The
    invariant checker verifies checkpoints with its own independent hashing, so
    a plan carrying this fault MUST produce a red report; a green one means the
    harness itself can no longer detect regressions."""

    def __init__(self, session: ChaosSession):
        self.session = session
        self._original = None

    def __enter__(self) -> "HarnessInjector":
        from .. import checkpointing

        if self.session.fire("harness.disable_verification"):
            self._original = checkpointing.verify_checkpoint_dir
            checkpointing.verify_checkpoint_dir = lambda directory: True
        return self

    def __exit__(self, *exc):
        if self._original is not None:
            from .. import checkpointing

            checkpointing.verify_checkpoint_dir = self._original
            self._original = None
        return False


def catalog() -> Dict[str, str]:
    """The fault-kind catalog (`accelerate-tpu chaos list-faults`)."""
    return dict(FAULT_KINDS)

"""Subprocess chaos workload: the worker side of the ``ACCELERATE_TPU_FAULT_PLAN``
env protocol.

``python -m accelerate_tpu.chaos.workload --base-dir DIR --steps N`` runs the
tiny supervised training loop under whatever plan the environment carries —
real signals this time (`proc.sigkill` is an actual SIGKILL, `proc.sigterm`
exercises the real `PreemptionHandler` -> `check_preemption()` -> exit-143
handoff) — and journals its evidence to ``DIR/chaos_journal.jsonl`` for the
`ChaosRunner.run_supervised_train` invariant checks. Each journal line is one
JSON record flushed before the next step, so a SIGKILL tears at most the line
in flight (the reader skips it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..telemetry.tracing import default_tracer
from .injectors import ChaosSession, FilesystemInjector, HarnessInjector, StepBoundaryInjector
from .plan import FaultPlan
from .runner import (
    build_train_workload,
    manifest_step,
    opt_state_data_sharded,
    params_digest,
    resume_evidence,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("accelerate-tpu chaos workload")
    parser.add_argument("--base-dir", required=True, help="project dir (checkpoints + journal)")
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument("--keep-last-n", type=int, default=3)
    parser.add_argument(
        "--async-save", action="store_true",
        help="save through the background committer (snapshot-then-commit): a real "
        "SIGKILL at a step boundary then lands while the commit is genuinely in "
        "flight on another thread",
    )
    parser.add_argument(
        "--mesh-2d", action="store_true",
        help="train the small MLP on the (\"data\", \"model\") mesh with "
        "sharding_rules=\"auto\" (planner 2D plan, ZeRO data-sharded Adam "
        "moments) and journal the optimizer-state layout for the "
        "zero_state_sharded invariant",
    )
    args = parser.parse_args(argv)

    plan = FaultPlan.from_env() or FaultPlan(name="empty")
    # The worker side of the trace env protocol: ACCELERATE_TPU_TRACE_DIR/_ID/
    # _PARENT (injected by the Supervisor) stream this attempt's spans into
    # the shared trace dir, parented under the supervisor's attempt span —
    # spans completed before a SIGKILL survive as the timeline's evidence.
    tracer = default_tracer()
    session = ChaosSession(plan, tracer=tracer)
    journal_path = os.path.join(args.base_dir, "chaos_journal.jsonl")
    os.makedirs(args.base_dir, exist_ok=True)
    journal_file = open(journal_path, "a")

    def journal(record: dict):
        journal_file.write(json.dumps(record) + "\n")
        journal_file.flush()
        os.fsync(journal_file.fileno())

    # Persist each injection record BEFORE its fault lands: a SIGKILL firing at
    # a step boundary must not erase the evidence that it fired.
    session.on_inject = lambda entry: journal({"type": "injection", **entry})
    journal({"type": "attempt", "pid": os.getpid()})

    accelerator, model, opt, pdl = build_train_workload(
        args.base_dir, args.keep_last_n, plan.seed, async_save=args.async_save,
        mesh_2d=args.mesh_2d,
    )
    accelerator.register_preemption_checkpoint()  # real SIGTERM latch + exit 143
    if args.mesh_2d:
        # The layout evidence BEFORE any fault lands: this attempt's optimizer
        # state is live-sharded along "data" (the planner's ZeRO placement).
        journal({
            "type": "layout",
            "pid": os.getpid(),
            "zero_state_sharded": opt_state_data_sharded(opt),
        })

    boundary = StepBoundaryInjector(session, hard=True)
    attempt_span = tracer.start_span("train.attempt", category="train", pid=os.getpid())
    with tracer.activate(attempt_span), FilesystemInjector(session), HarnessInjector(session):
        manager = accelerator.checkpoint_manager()
        start_step = 0
        try:
            resolved = manager.resolve("latest")
        except FileNotFoundError:
            resolved = None
        if resolved is not None:
            accelerator.load_state("latest")
            evidence = resume_evidence(
                resolved, model, manager.base_dir,
                opt=opt if args.mesh_2d else None,
            )
            journal({"type": "resume", **evidence})
            resumed_step = evidence["step"]
            start_step = (resumed_step if resumed_step is not None else -1) + 1
            tracer.event("train.resume", step=resumed_step, category="train")

        def batches():
            while True:
                for b in pdl:
                    yield b

        stream = batches()
        for step in range(start_step, args.steps):
            with tracer.span("train.step", category="train", step=step):
                batch = next(stream)
                accelerator.backward(model.loss, batch)
                opt.step()
                opt.zero_grad()
                digest = params_digest(model)
                intended_step = accelerator.save_iteration
                journal({"type": "intent", "step": intended_step, "digest": digest})
                path = accelerator.save_state()
                journal({
                    "type": "save",
                    # Async: the manifest lands when the background commit
                    # publishes; the intended step is the journal record.
                    "step": intended_step if args.async_save else manifest_step(path),
                    "digest": digest,
                    "path": path,
                })
            boundary.poll(step)
            accelerator.poll_async_checkpoint()
            if accelerator.preemption_requested:
                # Journal the preemption checkpoint's intent first: params are
                # unchanged since this step's save, so the digest carries over.
                journal({
                    "type": "intent", "step": accelerator.save_iteration, "digest": digest,
                })
                journal({"type": "graceful_exit", "step": step})
                attempt_span.annotate(outcome="preempted").end()
                accelerator.check_preemption()  # flushes async commits, saves + SystemExit(143)
        # A completed run's last background commit must be durable before the
        # worker reports success to the Supervisor.
        accelerator.drain_checkpoints()
    attempt_span.annotate(outcome="completed").end()
    return 0


if __name__ == "__main__":
    sys.exit(main())

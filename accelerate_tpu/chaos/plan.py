"""Declarative, JSON-serializable fault plans (the chaos subsystem's contract).

A `FaultPlan` is a seeded list of `FaultEvent`s: each names a fault *kind* from
the injector catalog (`FAULT_KINDS`) plus the trigger that arms it — a step
index (`at_step`), an N-th-matching-call count (`at_call`), a wall-clock offset
from plan arm (`after_s`), and/or a filename glob (`path_pattern`) for
filesystem faults. All specified trigger conditions AND together; `times`
bounds how often an event fires (default once, `0` = every match). Everything
is plain JSON, so a plan written once replays byte-identically — determinism is
the point: a chaos failure must be a repro, not an anecdote.

Plans reach launched worker processes through the ``ACCELERATE_TPU_FAULT_PLAN``
environment variable (a path to a plan file, or inline JSON), the same
two-sided protocol as the profiler's ``ACCELERATE_TPU_PROFILE_DIR``:
`accelerate-tpu launch --fault_plan plan.json` exports it, and the worker-side
workload re-arms via `FaultPlan.from_env()`.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

#: Env var carrying the plan to launched workers (path to a JSON file, or
#: inline JSON when the value starts with "{").
FAULT_PLAN_ENV = "ACCELERATE_TPU_FAULT_PLAN"

#: The injector catalog: every fault kind the subsystem can inject, with the
#: seam it fires at. `accelerate-tpu chaos list-faults` prints this table.
FAULT_KINDS: Dict[str, str] = {
    "fs.torn_write": (
        "post-commit corruption: truncate (or bit-flip with args.flip) a matching artifact of a "
        "just-published checkpoint at args.offset bytes / args.offset_frac of its size"
    ),
    "fs.io_error": (
        "raise OSError(args.errno: ENOSPC|EIO, default EIO) from a matching artifact write or "
        "checkpoint-directory publish rename (transient-I/O / full-disk faults)"
    ),
    "fs.slow_fsync": "stall args.delay_s seconds (default 0.05) inside a matching artifact's fsync",
    "fs.crash_in_rename": (
        "die (InjectedKill) inside a rename window: atomic_write's (payload fsynced, os.replace "
        "not yet run) for a matching artifact, or CheckpointManager._publish's directory rename "
        "for a matching checkpoint dir (pattern 'checkpoint_*') — on an async save this kills "
        "the background committer mid-commit"
    ),
    "proc.sigkill": (
        "hard kill at a matching step boundary: SIGKILL to self in subprocess workloads, "
        "InjectedKill (a BaseException no handler may swallow) in-process"
    ),
    "proc.sigterm": (
        "deliver SIGTERM to self at a matching step boundary or artifact write "
        "(exercises the PreemptionHandler latch mid-commit)"
    ),
    "backend.recompile": "force a full retrace (jax.clear_caches()) at a matching step boundary",
    "serve.dispatch_error": (
        "a matching decode-chunk dispatch raises InjectedBackendError (the shared-executable "
        "blast radius: every in-flight request errors, the engine must survive); "
        "args.consume_donated additionally deletes the donated cache buffers, modeling an "
        "accelerator dispatch that failed AFTER consuming its operands"
    ),
    "serve.dispatch_stall": "sleep args.delay_s (default 0.05) before a matching decode-chunk dispatch",
    "serve.insert_error": (
        "a matching insert (admission) dispatch raises (isolated to one request); "
        "args.consume_donated deletes the donated cache buffers first (accelerator semantics)"
    ),
    "serve.queue_burst": (
        "submit args.count (default 8) extra requests in one burst at a matching serve step "
        "(drives the bounded queue into QueueFull backpressure)"
    ),
    "router.replica_kill": (
        "kill one replica of a serving Router mid-traffic: the replica's decode dispatch "
        "raises InjectedKill (the in-process analogue of a worker SIGKILL — no engine "
        "handler may swallow it), the router must eject it, re-dispatch never-streamed "
        "requests and surface finish_reason=replica_lost for streamed ones. Target via "
        "path_pattern 'replica_N' (at_call counts that replica's dispatches)"
    ),
    "router.replica_stall": (
        "stall args.delay_s (default 0.05) before one replica's decode dispatch (the "
        "degraded-health signal); target via path_pattern 'replica_N'"
    ),
    "router.replica_poison": (
        "one replica's decode dispatch raises InjectedBackendError (the engine-level "
        "blast radius: its in-flight requests error, the replica survives and the router's "
        "failure counters observe it); target via path_pattern 'replica_N'"
    ),
    "fleet.worker_kill": (
        "deliver a REAL SIGKILL to a subprocess engine worker at a matching step op "
        "(worker-side, via the env-propagated plan): the controller's recv sees EOF, the "
        "router ejects the replica, re-dispatches never-streamed work, and the factory "
        "respawns a warm worker. Target via path_pattern 'worker_N' (at_call counts that "
        "worker's step ops); firings are journaled to ACCELERATE_TPU_CHAOS_JOURNAL before "
        "the kill and pre-consumed on restart so a respawned worker cannot re-kill itself"
    ),
    "fleet.worker_stall": (
        "sleep args.delay_s (default 1.0) inside a worker before handling a matching step "
        "op — stall PAST the controller's step timeout and the hang surfaces exactly like "
        "a death (heartbeat expiry -> kill -> eject -> respawn); target via path_pattern "
        "'worker_N'"
    ),
    "net.partition": (
        "drop one worker's socket transport for args.window_s seconds (default 0.5): the "
        "link severs, every frame raises until the window heals, then reconnect must "
        "succeed — a healed partition is a RECONNECT (controller re-handshake + stream "
        "reconciliation), never a worker respawn; a window longer than the controller's "
        "reconnect_deadline_s escalates to the ordinary warm respawn path. Target via "
        "path_pattern 'worker_N' (at_call counts that worker's frame sends); socket "
        "fleets only (run_fleet transport='socket')"
    ),
    "net.slow": (
        "inject latency past the frame deadline on one worker's transport: a matching "
        "frame send raises FrameTimeout (the slow-network face of a partition — the "
        "frames are fine, the deadline is not); the controller must treat it exactly "
        "like a torn link (reconnect, not respawn). Target via path_pattern 'worker_N'; "
        "socket fleets only"
    ),
    "net.flap": (
        "repeated short partitions: each firing severs the link for args.window_s "
        "seconds (default 0.1); set times=N for N flaps. Every flap must heal via "
        "reconnect with streams intact — the flap count reconciles against the worker's "
        "re-registration journal. Target via path_pattern 'worker_N'; socket fleets only"
    ),
    "harness.disable_verification": (
        "seeded-regression fixture: neuter checkpoint digest verification so torn checkpoints "
        "resolve — the invariant report MUST go red (proves the harness detects regressions)"
    ),
}


@dataclass
class FaultEvent:
    """One scripted fault. Trigger fields AND together; unset fields don't
    constrain. `times` caps total firings (1 = once, 0 = every match)."""

    kind: str
    at_step: Optional[int] = None
    at_call: Optional[int] = None
    after_s: Optional[float] = None
    path_pattern: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)
    times: int = 1

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known kinds: {sorted(FAULT_KINDS)}"
            )
        if self.times < 0:
            raise ValueError("times must be >= 0 (0 = unlimited)")

    def to_dict(self) -> dict:
        out = asdict(self)
        # Compact serialization: drop unset trigger fields and empty args.
        return {k: v for k, v in out.items() if v not in (None, {}) or k in ("kind", "times")}

    @classmethod
    def from_dict(cls, data: dict) -> "FaultEvent":
        known = {"kind", "at_step", "at_call", "after_s", "path_pattern", "args", "times"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown FaultEvent field(s) {sorted(unknown)} in {data!r}")
        return cls(**data)


#: Workloads a plan may declare as its intended harness (`ChaosRunner` entry
#: points; the CLI's default when `--workload` is omitted).
PLAN_WORKLOADS = ("train", "async-train", "serve", "supervised-train", "router", "fleet")


@dataclass
class FaultPlan:
    """A named, seeded fault schedule. The seed drives every random choice a
    chaos workload makes (data, prompts), so one plan is one exact repro.
    `workload` optionally names the harness the plan was written against
    (e.g. ``async-train`` for the async-commit-boundary sweeps)."""

    name: str = "chaos"
    seed: int = 0
    events: List[FaultEvent] = field(default_factory=list)
    notes: str = ""
    workload: Optional[str] = None

    def __post_init__(self):
        self.events = [
            ev if isinstance(ev, FaultEvent) else FaultEvent.from_dict(ev) for ev in self.events
        ]
        if self.workload is not None and self.workload not in PLAN_WORKLOADS:
            raise ValueError(
                f"unknown plan workload {self.workload!r}; known: {PLAN_WORKLOADS}"
            )

    # ------------------------------------------------------------------ (de)serialization
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "events": [ev.to_dict() for ev in self.events],
            **({"notes": self.notes} if self.notes else {}),
            **({"workload": self.workload} if self.workload else {}),
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, data: dict) -> "FaultPlan":
        return cls(
            name=data.get("name", "chaos"),
            seed=int(data.get("seed", 0)),
            events=[FaultEvent.from_dict(ev) for ev in data.get("events", [])],
            notes=data.get("notes", ""),
            workload=data.get("workload"),
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> str:
        path = str(path)
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(f.read())

    # ------------------------------------------------------------------ env protocol
    @classmethod
    def from_env(cls, environ: Optional[Dict[str, str]] = None) -> Optional["FaultPlan"]:
        """Read the launch-propagated plan: ``ACCELERATE_TPU_FAULT_PLAN`` is a
        path to a plan file, or inline JSON when it starts with ``{``. Returns
        None when the env var is unset (no chaos armed)."""
        value = (environ if environ is not None else os.environ).get(FAULT_PLAN_ENV)
        if not value:
            return None
        value = value.strip()
        if value.startswith("{"):
            return cls.from_json(value)
        return cls.load(value)


# ------------------------------------------------------------------ builtin plans
def builtin_plans() -> Dict[str, FaultPlan]:
    """Named plans shipped with the CLI (`accelerate-tpu chaos run --plan NAME`).

    `smoke-train` / `smoke-serve` are the clean fixtures (faults injected, every
    invariant must hold, exit 0); `seeded-regression` deliberately neuters
    digest verification so a torn manifest resolves — the run MUST exit
    non-zero with a violated-invariant report, proving the harness can tell a
    broken stack from a healthy one.
    """
    return {
        "smoke-train": FaultPlan(
            name="smoke-train",
            seed=0,
            notes="SIGKILL at a step boundary + SIGTERM inside a staged commit + a slow fsync: "
            "the train recovery chain end to end",
            events=[
                FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz*", at_call=1,
                           args={"delay_s": 0.05}),
                FaultEvent(kind="proc.sigkill", at_step=1),
                FaultEvent(kind="proc.sigterm", path_pattern="model.npz*", at_call=4),
            ],
        ),
        "smoke-serve": FaultPlan(
            name="smoke-serve",
            seed=0,
            notes="dispatch stall + queue-full burst + one dispatch failure: every request must "
            "still reach a terminal finish_reason",
            events=[
                FaultEvent(kind="serve.dispatch_stall", at_call=2, args={"delay_s": 0.02}),
                FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
                FaultEvent(kind="serve.dispatch_error", at_call=4),
            ],
        ),
        "smoke-async-ckpt": FaultPlan(
            name="smoke-async-ckpt",
            seed=0,
            workload="async-train",
            notes="async-checkpoint recovery chain: a SIGKILL lands while a slowed background "
            "commit is still in flight (the commit must not publish after the death), a later "
            "committer dies inside an artifact's rename window, and a post-publish torn write "
            "corrupts the newest checkpoint — resume exactness and no-torn-resolved must hold "
            "with every commit running on the background committer",
            events=[
                # Slow the step-1 commit's model fsync so the step-boundary kill
                # below fires while that commit is provably still in flight.
                FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz*", at_call=2,
                           args={"delay_s": 0.25}),
                FaultEvent(kind="proc.sigkill", at_step=1),
                # After the restart: a committer death inside an artifact's
                # rename window (the commit must abort unpublished).
                FaultEvent(kind="fs.crash_in_rename", path_pattern="optimizer.npz*", at_call=5),
                # And a post-publish torn write: resolve() must fall back.
                FaultEvent(kind="fs.torn_write", path_pattern="model.npz*", at_call=6,
                           args={"offset": 1}),
            ],
        ),
        "smoke-router": FaultPlan(
            name="smoke-router",
            seed=0,
            workload="router",
            notes="replicated-fleet degradation chain: stall one replica (degraded), poison "
            "another's dispatch (blast radius, replica survives), then kill a third outright "
            "(eject -> re-dispatch/replica_lost -> rejoin) — every request must reach a "
            "terminal finish_reason, no token stream may duplicate, the fleet must recover, "
            "and the router must never route to an ejected replica",
            events=[
                # Burst first so least-loaded routing actually spreads work
                # over the whole fleet (per-replica at_call triggers below
                # count each replica's OWN dispatches).
                FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 8}),
                FaultEvent(kind="router.replica_stall", path_pattern="replica_1", at_call=2,
                           args={"delay_s": 0.02}),
                FaultEvent(kind="router.replica_poison", path_pattern="replica_2", at_call=2),
                FaultEvent(kind="router.replica_kill", path_pattern="replica_0", at_call=4),
            ],
        ),
        "smoke-fleet": FaultPlan(
            name="smoke-fleet",
            seed=0,
            workload="fleet",
            notes="out-of-process fleet degradation chain over REAL worker processes: a "
            "queue burst spreads load, one worker stalls past the controller's step "
            "timeout (heartbeat-expiry kill -> respawn), another takes a real SIGKILL "
            "mid-traffic (eject -> re-dispatch/replica_lost -> warm respawn) — every "
            "request must reach a terminal finish_reason, no token stream may duplicate, "
            "restarted workers must rejoin warm, and the ledger must reconcile the "
            "worker-side journal against observed process deaths",
            events=[
                FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
                FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=4),
                FaultEvent(kind="fleet.worker_stall", path_pattern="worker_1", at_call=6,
                           args={"delay_s": 30.0}),
            ],
        ),
        "partition-fleet": FaultPlan(
            name="partition-fleet",
            seed=0,
            workload="fleet",
            notes="network-chaos chain over a SOCKET fleet (run with transport='socket'): a "
            "queue burst spreads load, one worker's link partitions for a healable window "
            "(reconnect + stream reconciliation, NOT respawn), another's frames slow past "
            "the deadline (must surface as the same transport fault), and a third flaps "
            "twice — every request must reach a terminal finish reason, no stream may "
            "duplicate across reconnects, healed partitions must not increment respawn "
            "counters, and the controller's reconnect ledger must reconcile against the "
            "workers' re-registration journal",
            events=[
                FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
                FaultEvent(kind="net.partition", path_pattern="worker_0", at_call=4,
                           args={"window_s": 0.4}),
                FaultEvent(kind="net.slow", path_pattern="worker_1", at_call=6),
                # Two flaps as two events: at_call is an EXACT Nth-call match,
                # so a single times=2 event could never fire its second flap.
                FaultEvent(kind="net.flap", path_pattern="worker_0", at_call=12,
                           args={"window_s": 0.1}),
                FaultEvent(kind="net.flap", path_pattern="worker_0", at_call=18,
                           args={"window_s": 0.1}),
            ],
        ),
        "seeded-regression": FaultPlan(
            name="seeded-regression",
            seed=0,
            notes="regression fixture: verification disabled + torn manifest -> the invariant "
            "report must go red (non-zero exit)",
            events=[
                FaultEvent(kind="harness.disable_verification"),
                FaultEvent(kind="fs.torn_write", path_pattern="MANIFEST.json", at_call=2,
                           args={"offset": 0}),
                # Kill IMMEDIATELY after the torn publish: the torn checkpoint
                # is the newest, so the neutered resolver hands it to resume.
                FaultEvent(kind="proc.sigkill", at_step=1),
            ],
        ),
    }

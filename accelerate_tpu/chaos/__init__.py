"""Deterministic fault injection + end-to-end recovery invariants (L5 robustness).

The resilience layer (crash-safe checkpoints, restarting Supervisor,
fault-isolated serving, goodput ledger) is only as trustworthy as the faults it
has actually survived. This package makes those faults *scripted, seeded and
replayable*:

  - `plan` — JSON-serializable `FaultPlan`/`FaultEvent` schedules (triggers by
    step index, call count, wall-clock offset, path pattern), propagated to
    launched workers via ``ACCELERATE_TPU_FAULT_PLAN``.
  - `injectors` — composable injectors at the seams the code already owns:
    filesystem (torn writes, ENOSPC/EIO, slow fsync, rename-window crashes),
    process (SIGKILL at step N, SIGTERM mid-save), backend/serving (stalled or
    failing dispatches, queue-full bursts, forced retraces), plus a `FakeClock`
    for backoff/deadline tests. Every firing counts in
    ``chaos_injected_total{kind=...}``.
  - `runner` — `ChaosRunner` executes train/serve workloads under a plan and
    emits an `InvariantReport`: resume exactness, no-torn-checkpoint-resolved,
    restart/downtime budgets, terminal finish reasons on drain, and
    ledger/counter reconciliation.
  - `workload` — the subprocess worker (`python -m accelerate_tpu.chaos.workload`)
    the real-`Supervisor` path drives.

CLI: ``accelerate-tpu chaos run|list-faults|report`` (docs/fault_tolerance.md).
Importing this package never touches jax — workloads import it lazily.
"""

from .injectors import (
    ChaosSession,
    FakeClock,
    FilesystemInjector,
    HarnessInjector,
    InjectedBackendError,
    InjectedKill,
    RouterInjector,
    ServingInjector,
    StepBoundaryInjector,
    catalog,
)
from .plan import FAULT_KINDS, FAULT_PLAN_ENV, FaultEvent, FaultPlan, builtin_plans
from .runner import ChaosRunner, InvariantCheck, InvariantReport

__all__ = [
    "FAULT_KINDS",
    "FAULT_PLAN_ENV",
    "FaultEvent",
    "FaultPlan",
    "builtin_plans",
    "catalog",
    "ChaosSession",
    "FakeClock",
    "FilesystemInjector",
    "HarnessInjector",
    "InjectedBackendError",
    "InjectedKill",
    "RouterInjector",
    "ServingInjector",
    "StepBoundaryInjector",
    "ChaosRunner",
    "InvariantCheck",
    "InvariantReport",
]

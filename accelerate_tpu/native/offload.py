"""Native disk offload store: one binary blob + JSON index, parallel pread + async
readahead (the perf-bearing replacement for the reference's per-tensor .dat mmap files,
utils/offload.py:25-192 — same role, single-file layout, C++ read path).

Write path is plain Python (offload writes are cold); the hot path — streaming layer
weights back while earlier layers compute — uses the thread pool for striped pread and
`prefetch()` tickets for overlap. Numpy-only fallback reads with np.fromfile.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np


class NativeOffloadStore:
    """Tensor name -> (offset, shape, dtype) in one blob file."""

    INDEX_NAME = "index.json"
    BLOB_NAME = "weights.bin"

    # Single-chunk reads (below the C++ stripe floor) run inline on the calling
    # thread: the pool adds only wakeup latency for them (~1ms on a busy host).
    INLINE_READ_BYTES = 8 << 20

    def __init__(self, directory: str, num_threads: int = 4):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.index_path = os.path.join(directory, self.INDEX_NAME)
        self.blob_path = os.path.join(directory, self.BLOB_NAME)
        self.index: Dict[str, dict] = {}
        if os.path.exists(self.index_path):
            with open(self.index_path) as f:
                self.index = json.load(f)
        from . import load_library

        self.lib = load_library()
        # More workers than cores is pure context-switch overhead (pread from the
        # page cache is CPU/memory-bandwidth work, not blocking I/O waits).
        num_threads = max(1, min(int(num_threads), os.cpu_count() or 1))
        self._pool = self.lib.atl_pool_create(int(num_threads)) if self.lib else None
        self._read_fd: Optional[int] = None
        # Readahead needs a core for the worker to run on; on a 1-core host a
        # background read cannot overlap anything and just adds handoffs, so
        # group prefetch degrades to (fast) inline reads at read() time.
        self._allow_prefetch = (os.cpu_count() or 1) > 1
        self._store = None
        self._tickets: Dict[str, tuple] = {}

    # -- write --------------------------------------------------------------------
    def save(self, tensors: Dict[str, np.ndarray], flush_index: bool = True):
        """Append tensors to the blob and update the index.

        Callers spilling many tensors one at a time (to bound host RAM) pass
        `flush_index=False` and call `flush_index()` once at the end — the index
        rewrite is O(total tensors), so flushing per call would be O(n²)."""
        self._close_store()
        mode = "ab" if os.path.exists(self.blob_path) else "wb"
        with open(self.blob_path, mode) as f:
            for name, arr in tensors.items():
                arr = np.ascontiguousarray(arr)
                offset = f.tell()
                f.write(arr.tobytes())
                self.index[name] = {
                    "offset": offset,
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                }
        if flush_index:
            self.flush_index()

    def flush_index(self):
        with open(self.index_path, "w") as f:
            json.dump(self.index, f)

    def reset(self):
        """Start a fresh blob, discarding any existing contents in the directory.

        Writers that re-create a store over an existing directory (re-dispatching
        a model, optimizer re-init) must start clean: `save`'s append-then-repoint
        layout would orphan the old bytes and grow the blob by a full copy per run."""
        self._close_store()
        for path in (self.blob_path, self.index_path):
            if os.path.exists(path):
                os.unlink(path)
        if self._read_fd is not None:
            os.close(self._read_fd)
            self._read_fd = None
        self.index = {}

    def write(self, name: str, arr: np.ndarray):
        """In-place update of an existing tensor (same byte size), else append.

        The update path that makes the store usable for MUTABLE state (the disk
        optimizer tier rewrites every group each step — `save`'s append-only
        layout would grow the blob without bound)."""
        arr = np.ascontiguousarray(arr)
        meta = self.index.get(name)
        if meta is not None:
            shape = tuple(meta["shape"])
            dtype = np.dtype(meta["dtype"])
            nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
            if arr.nbytes == nbytes:
                # Same slot: overwrite bytes at the recorded offset. Readers use
                # pread on the same file, so subsequent reads see the new data.
                with open(self.blob_path, "r+b") as f:
                    f.seek(meta["offset"])
                    f.write(arr.tobytes())
                if list(arr.shape) != meta["shape"] or str(arr.dtype) != meta["dtype"]:
                    meta["shape"], meta["dtype"] = list(arr.shape), str(arr.dtype)
                    with open(self.index_path, "w") as f:
                        json.dump(self.index, f)
                return
        self.save({name: arr})

    # -- read ---------------------------------------------------------------------
    def _open_store(self):
        if self._store is None and self.lib is not None:
            self._store = self.lib.atl_store_open(self.blob_path.encode())
        return self._store

    def _close_store(self):
        if self._store is not None:
            self.lib.atl_store_close(self._store)
            self._store = None

    def keys(self):
        return self.index.keys()

    def __contains__(self, name):
        return name in self.index

    def _meta(self, name):
        meta = self.index[name]
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        return meta["offset"], shape, dtype, nbytes

    def _pread_into(self, out: np.ndarray, offset: int, nbytes: int):
        """Inline positional read on the calling thread (no pool handoff)."""
        if self._read_fd is None:
            self._read_fd = os.open(self.blob_path, os.O_RDONLY)
        view = memoryview(out.reshape(-1).view(np.uint8))
        done = 0
        while done < nbytes:
            got = os.preadv(self._read_fd, [view[done:nbytes]], offset + done)
            if got <= 0:
                raise IOError(f"short read at {offset + done} in {self.blob_path}")
            done += got

    def read(self, name: str) -> np.ndarray:
        """Blocking read; consumes a pending prefetch for `name` when one exists."""
        if name in self._tickets:
            ticket, out, *group = self._tickets.pop(name)
            rc = self.lib.atl_wait_status(self._pool, ticket)
            if group:  # shared group ticket: this region's own status governs
                statuses, i = group
                rc = int(statuses[i])
            if rc != 0:
                raise IOError(f"prefetch read failed for {name!r} in {self.blob_path}")
            return out
        offset, shape, dtype, nbytes = self._meta(name)
        out = np.empty(shape, dtype=dtype)
        store = self._open_store()
        if store is None or nbytes <= self.INLINE_READ_BYTES:
            self._pread_into(out, offset, nbytes)
            return out
        rc = self.lib.atl_store_read(
            self._pool, store, offset, nbytes, out.ctypes.data_as(__import__("ctypes").c_void_p)
        )
        if rc != 0:
            raise IOError(f"short read for {name!r} in {self.blob_path}")
        return out

    def prefetch(self, name: str):
        """Start an async readahead for `name` (no-op without the native lib)."""
        store = self._open_store()
        if store is None or name in self._tickets:
            return
        offset, shape, dtype, nbytes = self._meta(name)
        out = np.empty(shape, dtype=dtype)
        import ctypes

        ticket = self.lib.atl_store_prefetch(
            self._pool, store, offset, nbytes, out.ctypes.data_as(ctypes.c_void_p)
        )
        self._tickets[name] = (ticket, out)

    def prefetch_many(self, names):
        """Async readahead of a whole group under ONE pool ticket.

        One queue handoff per layer/parameter-group instead of one per tensor —
        per-ticket submission latency dominates small-tensor readahead on a busy
        host. No-op without the native lib; names already in flight are skipped."""
        store = self._open_store() if self._allow_prefetch else None
        names = [n for n in names if n not in self._tickets] if store is not None else []
        if not names:
            return
        import ctypes

        n = len(names)
        offsets = (ctypes.c_int64 * n)()
        sizes = (ctypes.c_int64 * n)()
        dsts = (ctypes.c_void_p * n)()
        statuses = np.full(n, -2, np.int32)
        outs = []
        for i, name in enumerate(names):
            offset, shape, dtype, nbytes = self._meta(name)
            out = np.empty(shape, dtype=dtype)
            outs.append(out)
            offsets[i], sizes[i] = offset, nbytes
            dsts[i] = out.ctypes.data_as(ctypes.c_void_p)
        ticket = self.lib.atl_store_read_many(
            self._pool, store, n, offsets, sizes, dsts,
            statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        for i, name in enumerate(names):
            # The ticket is shared; the per-region status array keeps failures
            # attributable after the first wait has consumed the ticket.
            self._tickets[name] = (ticket, outs[i], statuses, i)

    def close(self):
        for entry in list(self._tickets.values()):
            self.lib.atl_wait(self._pool, entry[0])
        self._tickets.clear()
        self._close_store()
        if self._read_fd is not None:
            os.close(self._read_fd)
            self._read_fd = None
        if self._pool is not None:
            self.lib.atl_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

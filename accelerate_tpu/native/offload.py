"""Native disk offload store: one binary blob + JSON index, parallel pread + async
readahead (the perf-bearing replacement for the reference's per-tensor .dat mmap files,
utils/offload.py:25-192 — same role, single-file layout, C++ read path).

Write path is plain Python (offload writes are cold); the hot path — streaming layer
weights back while earlier layers compute — uses the thread pool for striped pread and
`prefetch()` tickets for overlap. Numpy-only fallback reads with np.fromfile.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np


class NativeOffloadStore:
    """Tensor name -> (offset, shape, dtype) in one blob file."""

    INDEX_NAME = "index.json"
    BLOB_NAME = "weights.bin"

    def __init__(self, directory: str, num_threads: int = 4):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.index_path = os.path.join(directory, self.INDEX_NAME)
        self.blob_path = os.path.join(directory, self.BLOB_NAME)
        self.index: Dict[str, dict] = {}
        if os.path.exists(self.index_path):
            with open(self.index_path) as f:
                self.index = json.load(f)
        from . import load_library

        self.lib = load_library()
        self._pool = self.lib.atl_pool_create(int(num_threads)) if self.lib else None
        self._store = None
        self._tickets: Dict[str, tuple] = {}

    # -- write --------------------------------------------------------------------
    def save(self, tensors: Dict[str, np.ndarray]):
        """Append tensors to the blob and update the index."""
        self._close_store()
        mode = "ab" if os.path.exists(self.blob_path) else "wb"
        with open(self.blob_path, mode) as f:
            for name, arr in tensors.items():
                arr = np.ascontiguousarray(arr)
                offset = f.tell()
                f.write(arr.tobytes())
                self.index[name] = {
                    "offset": offset,
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                }
        with open(self.index_path, "w") as f:
            json.dump(self.index, f)

    # -- read ---------------------------------------------------------------------
    def _open_store(self):
        if self._store is None and self.lib is not None:
            self._store = self.lib.atl_store_open(self.blob_path.encode())
        return self._store

    def _close_store(self):
        if self._store is not None:
            self.lib.atl_store_close(self._store)
            self._store = None

    def keys(self):
        return self.index.keys()

    def __contains__(self, name):
        return name in self.index

    def _meta(self, name):
        meta = self.index[name]
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        return meta["offset"], shape, dtype, nbytes

    def read(self, name: str) -> np.ndarray:
        """Blocking read; consumes a pending prefetch for `name` when one exists."""
        if name in self._tickets:
            ticket, out = self._tickets.pop(name)
            rc = self.lib.atl_wait_status(self._pool, ticket)
            if rc != 0:
                raise IOError(f"prefetch read failed for {name!r} in {self.blob_path}")
            return out
        offset, shape, dtype, nbytes = self._meta(name)
        store = self._open_store()
        if store is None:
            with open(self.blob_path, "rb") as f:
                f.seek(offset)
                return np.frombuffer(f.read(nbytes), dtype=dtype).reshape(shape).copy()
        out = np.empty(shape, dtype=dtype)
        rc = self.lib.atl_store_read(
            self._pool, store, offset, nbytes, out.ctypes.data_as(__import__("ctypes").c_void_p)
        )
        if rc != 0:
            raise IOError(f"short read for {name!r} in {self.blob_path}")
        return out

    def prefetch(self, name: str):
        """Start an async readahead for `name` (no-op without the native lib)."""
        store = self._open_store()
        if store is None or name in self._tickets:
            return
        offset, shape, dtype, nbytes = self._meta(name)
        out = np.empty(shape, dtype=dtype)
        import ctypes

        ticket = self.lib.atl_store_prefetch(
            self._pool, store, offset, nbytes, out.ctypes.data_as(ctypes.c_void_p)
        )
        self._tickets[name] = (ticket, out)

    def close(self):
        for name, (ticket, _out) in list(self._tickets.items()):
            self.lib.atl_wait(self._pool, ticket)
        self._tickets.clear()
        self._close_store()
        if self._pool is not None:
            self.lib.atl_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

"""Native (C++) host data plane: build + ctypes bindings.

The reference reaches native code through torch's C++ DataLoader workers and external
runtimes; here the in-tree `src/data_plane.cpp` provides the host-side hot paths
(GIL-free batch gather, parallel disk reads). Compiled on first use with the system
toolchain into `~/.cache/accelerate_tpu/` (or `ACCELERATE_TPU_NATIVE_CACHE`); every
consumer falls back to numpy paths when the toolchain or platform is unavailable
(`ACCELERATE_TPU_DISABLE_NATIVE=1` forces the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

from ..logging import get_logger

logger = get_logger(__name__)

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src", "data_plane.cpp")


def _cache_dir() -> str:
    return os.environ.get(
        "ACCELERATE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "accelerate_tpu"),
    )


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_cache_dir(), f"data_plane_{digest}.so")


def _build() -> str:
    path = _lib_path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, path)  # atomic: concurrent builders race benignly
    return path


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.atl_pool_create.argtypes = [c.c_int]
    lib.atl_pool_create.restype = c.c_void_p
    lib.atl_pool_destroy.argtypes = [c.c_void_p]
    lib.atl_pool_size.argtypes = [c.c_void_p]
    lib.atl_pool_size.restype = c.c_int
    lib.atl_gather_rows.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.POINTER(c.c_int64), c.c_int64, c.c_void_p]
    lib.atl_gather_submit.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_void_p),
        c.POINTER(c.c_int64),
        c.c_int,
        c.POINTER(c.c_int64),
        c.c_int64,
        c.POINTER(c.c_void_p),
    ]
    lib.atl_gather_submit.restype = c.c_int64
    lib.atl_wait.argtypes = [c.c_void_p, c.c_int64]
    lib.atl_wait_status.argtypes = [c.c_void_p, c.c_int64]
    lib.atl_wait_status.restype = c.c_int
    lib.atl_store_open.argtypes = [c.c_char_p]
    lib.atl_store_open.restype = c.c_void_p
    lib.atl_store_close.argtypes = [c.c_void_p]
    lib.atl_store_read.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_void_p]
    lib.atl_store_read.restype = c.c_int
    lib.atl_store_prefetch.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_void_p]
    lib.atl_store_prefetch.restype = c.c_int64
    lib.atl_store_read_many.argtypes = [
        c.c_void_p,
        c.c_void_p,
        c.c_int64,
        c.POINTER(c.c_int64),
        c.POINTER(c.c_int64),
        c.POINTER(c.c_void_p),
        c.POINTER(c.c_int32),
    ]
    lib.atl_store_read_many.restype = c.c_int64
    return lib


def native_available() -> bool:
    return load_library() is not None


def load_library() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None when unavailable."""
    global _LIB, _LOAD_FAILED
    if _LIB is not None:
        return _LIB
    if _LOAD_FAILED or os.environ.get("ACCELERATE_TPU_DISABLE_NATIVE") == "1":
        return None
    with _LOCK:
        if _LIB is not None:
            return _LIB
        try:
            _LIB = _bind(ctypes.CDLL(_build()))
        except Exception as e:  # toolchain missing, sandboxed fs, unsupported platform
            logger.warning("native data plane unavailable (%s); using numpy fallback", e)
            _LOAD_FAILED = True
            return None
    return _LIB


from .loader import ArrayDataset, NativeGatherPool  # noqa: E402
from .offload import NativeOffloadStore  # noqa: E402

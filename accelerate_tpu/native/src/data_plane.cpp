// Native host data plane for accelerate_tpu.
//
// TPU-native counterpart of the reference's native loader stack (torch DataLoader's
// C++ worker pool + pinned-memory collate, reached via data_loader.py; and the
// disk-offload mmap store, utils/offload.py:25-192). Two engines behind a tiny C ABI
// (bound from Python with ctypes — no pybind11 in the image):
//
//   1. Batch gather: a persistent thread pool copies selected rows of columnar
//      (contiguous) host arrays into caller-owned batch buffers, synchronously or as
//      async double-buffered tickets. This is the GIL-free replacement for
//      python-level `[dataset[i] for i in indices]` + np.stack collation.
//
//   2. Offload store: positional file reads (pread) parallelized across the pool,
//      plus async readahead tickets — the layer-streaming backend for big-model
//      disk offload (reference OffloadedWeightsLoader).
//
// Everything is plain C++17 + POSIX; built with `g++ -O3 -shared -fPIC -pthread`.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

// ------------------------------------------------------------------ thread pool
class Pool {
 public:
  explicit Pool(int n_threads) : stop_(false), next_ticket_(1) {
    if (n_threads < 1) n_threads = 1;
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { Run(); });
    }
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int size() const { return static_cast<int>(workers_.size()); }

  // Enqueue `n` subtasks under one ticket; ticket completes when all subtasks do.
  // A subtask returning nonzero marks the whole ticket failed (first error wins).
  // Zero subtasks complete the ticket immediately.
  int64_t Submit(std::vector<std::function<int()>> subtasks) {
    int64_t ticket = next_ticket_.fetch_add(1);
    if (subtasks.empty()) {
      std::unique_lock<std::mutex> lk(mu_);
      pending_[ticket] = TicketState{true, 0};
      done_cv_.notify_all();
      return ticket;
    }
    auto remaining = std::make_shared<std::atomic<int64_t>>(
        static_cast<int64_t>(subtasks.size()));
    {
      std::unique_lock<std::mutex> lk(mu_);
      pending_[ticket] = TicketState{false, 0};
      for (auto& fn : subtasks) {
        queue_.emplace_back([this, ticket, remaining, fn = std::move(fn)] {
          int rc = fn();
          std::unique_lock<std::mutex> lk(mu_);
          TicketState& st = pending_[ticket];
          if (rc != 0 && st.status == 0) st.status = rc;
          if (remaining->fetch_sub(1) == 1) {
            st.done = true;
            done_cv_.notify_all();
          }
        });
      }
    }
    cv_.notify_all();
    return ticket;
  }

  // Blocks until the ticket completes; returns its status (0 = ok).
  int Wait(int64_t ticket) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this, ticket] {
      auto it = pending_.find(ticket);
      return it == pending_.end() || it->second.done;
    });
    int status = 0;
    auto it = pending_.find(ticket);
    if (it != pending_.end()) {
      status = it->second.status;
      pending_.erase(it);
    }
    return status;
  }

 private:
  struct TicketState {
    bool done = false;
    int status = 0;
  };

  void Run() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        task = std::move(queue_.front());
        queue_.pop_front();
      }
      task();
    }
  }

  std::vector<std::thread> workers_;
  std::deque<std::function<void()>> queue_;
  std::unordered_map<int64_t, TicketState> pending_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  bool stop_;
  std::atomic<int64_t> next_ticket_;
};

// Split `n` rows across up to `shards` roughly even contiguous chunks.
std::vector<std::pair<int64_t, int64_t>> Chunks(int64_t n, int shards) {
  std::vector<std::pair<int64_t, int64_t>> out;
  if (n <= 0) return out;
  int64_t per = (n + shards - 1) / shards;
  for (int64_t start = 0; start < n; start += per) {
    out.emplace_back(start, std::min(per, n - start));
  }
  return out;
}

void GatherChunk(const char* src, int64_t row_bytes, const int64_t* indices,
                 int64_t start, int64_t count, char* dst) {
  for (int64_t r = start; r < start + count; ++r) {
    std::memcpy(dst + r * row_bytes, src + indices[r] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

struct Store {
  int fd;
};

}  // namespace

extern "C" {

// ------------------------------------------------------------------ pool
void* atl_pool_create(int num_threads) { return new Pool(num_threads); }

void atl_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

int atl_pool_size(void* pool) { return static_cast<Pool*>(pool)->size(); }

// ------------------------------------------------------------------ batch gather
// Copy rows `indices[0..n)` of `src` (row_bytes each) into dst, in parallel.
void atl_gather_rows(void* pool, const void* src, int64_t row_bytes,
                     const int64_t* indices, int64_t n, void* dst) {
  Pool* p = static_cast<Pool*>(pool);
  std::vector<std::function<int()>> tasks;
  for (auto [start, count] : Chunks(n, p->size())) {
    tasks.push_back([=] {
      GatherChunk(static_cast<const char*>(src), row_bytes, indices, start,
                  count, static_cast<char*>(dst));
      return 0;
    });
  }
  p->Wait(p->Submit(std::move(tasks)));
}

// Async gather over multiple columns under one ticket: column c copies rows
// `indices` from srcs[c] (row_bytes[c] each) into dsts[c].
int64_t atl_gather_submit(void* pool, const void** srcs,
                          const int64_t* row_bytes, int n_cols,
                          const int64_t* indices, int64_t n_rows, void** dsts) {
  Pool* p = static_cast<Pool*>(pool);
  std::vector<std::function<int()>> tasks;
  for (int c = 0; c < n_cols; ++c) {
    const char* src = static_cast<const char*>(srcs[c]);
    char* dst = static_cast<char*>(dsts[c]);
    int64_t rb = row_bytes[c];
    // Subdivide large columns so one wide column still uses the whole pool.
    int shards = std::max(1, p->size() / n_cols);
    for (auto [start, count] : Chunks(n_rows, shards)) {
      tasks.push_back([=] {
        GatherChunk(src, rb, indices, start, count, dst);
        return 0;
      });
    }
  }
  return p->Submit(std::move(tasks));
}

void atl_wait(void* pool, int64_t ticket) {
  static_cast<Pool*>(pool)->Wait(ticket);
}

// Blocking wait that surfaces the ticket's status (0 = ok, -1 = failed subtask).
int atl_wait_status(void* pool, int64_t ticket) {
  return static_cast<Pool*>(pool)->Wait(ticket);
}

// ------------------------------------------------------------------ offload store
void* atl_store_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  return new Store{fd};
}

void atl_store_close(void* store) {
  Store* s = static_cast<Store*>(store);
  if (s) {
    ::close(s->fd);
    delete s;
  }
}

int64_t atl_store_prefetch(void* pool, void* store, int64_t offset,
                           int64_t nbytes, void* dst);

// Parallel positional read of [offset, offset+nbytes) into dst. Returns 0 on
// success, -1 on a short/failed read.
int atl_store_read(void* pool, void* store, int64_t offset, int64_t nbytes,
                   void* dst) {
  Pool* p = static_cast<Pool*>(pool);
  return p->Wait(atl_store_prefetch(pool, store, offset, nbytes, dst));
}

// Async readahead ticket for the same read; failure is recorded on the ticket
// and surfaced by atl_wait_status.
int64_t atl_store_prefetch(void* pool, void* store, int64_t offset,
                           int64_t nbytes, void* dst) {
  Pool* p = static_cast<Pool*>(pool);
  Store* s = static_cast<Store*>(store);
  std::vector<std::function<int()>> tasks;
  // Stripe only past a floor (8 MiB per subtask): layer streaming prefetches
  // many modest tensors at once, and splitting each of those 8 ways just
  // multiplies queue/lock traffic — their parallelism comes from the tensors
  // already being concurrent tickets. A single huge read still stripes.
  constexpr int64_t kMinStripe = int64_t(8) << 20;
  int shards = static_cast<int>(
      std::min<int64_t>(p->size(), (nbytes + kMinStripe - 1) / kMinStripe));
  for (auto [start, count] : Chunks(nbytes, shards < 1 ? 1 : shards)) {
    tasks.push_back([=] {
      int64_t done = 0;
      while (done < count) {
        ssize_t got = ::pread(s->fd, static_cast<char*>(dst) + start + done,
                              static_cast<size_t>(count - done),
                              offset + start + done);
        if (got <= 0) return -1;
        done += got;
      }
      return 0;
    });
  }
  return p->Submit(std::move(tasks));
}

// Group readahead: read n regions under ONE ticket (one queue handoff for a
// whole layer/parameter-group instead of one per tensor — the handoff, not the
// pread, is what costs on a busy host). Regions are distributed round-robin
// across up to pool-size subtasks; statuses[i] (caller-owned, length n) is
// written 0/-1 per region and outlives the ticket, so a failure is still
// attributable after the shared ticket has been waited on once.
int64_t atl_store_read_many(void* pool, void* store, int64_t n,
                            const int64_t* offsets, const int64_t* nbytes,
                            void** dsts, int32_t* statuses) {
  Pool* p = static_cast<Pool*>(pool);
  Store* s = static_cast<Store*>(store);
  int shards = std::max(1, std::min<int>(p->size(), static_cast<int>(n)));
  // Copy the region tables: the caller's arrays need not outlive this call
  // (the Python binding builds them as temporaries); `statuses` and the
  // destination buffers are caller-owned and must stay alive until the wait.
  auto offs = std::make_shared<std::vector<int64_t>>(offsets, offsets + n);
  auto sizes = std::make_shared<std::vector<int64_t>>(nbytes, nbytes + n);
  auto outs = std::make_shared<std::vector<void*>>(dsts, dsts + n);
  std::vector<std::function<int()>> tasks;
  for (int w = 0; w < shards; ++w) {
    tasks.push_back([=] {
      int bad = 0;
      for (int64_t i = w; i < n; i += shards) {
        int64_t done = 0;
        int32_t st = 0;
        while (done < (*sizes)[i]) {
          ssize_t got = ::pread(s->fd, static_cast<char*>((*outs)[i]) + done,
                                static_cast<size_t>((*sizes)[i] - done),
                                (*offs)[i] + done);
          if (got <= 0) {
            st = -1;
            break;
          }
          done += got;
        }
        statuses[i] = st;
        if (st != 0) bad = 1;
      }
      return bad ? -1 : 0;
    });
  }
  return p->Submit(std::move(tasks));
}

}  // extern "C"

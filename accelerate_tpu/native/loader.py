"""Columnar dataset + native batch gather.

`ArrayDataset` holds a dict of contiguous numpy columns (the columnar layout every
high-throughput loader converges on). `NativeGatherPool` assembles batches by copying
the sampled rows of every column into preallocated batch buffers on C++ threads —
synchronously or one batch ahead (`submit`/`wait` double buffering). Falls back to
numpy fancy-indexing when the native library is unavailable; results are bit-identical.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence

import numpy as np


class ArrayDataset:
    """Map-style dataset over contiguous columnar arrays (all sharing dim 0).

    Indexing yields a dict row (SimpleDataLoader compatible); the fast path is
    batch-level gather via NativeGatherPool.
    """

    def __init__(self, columns: Dict[str, np.ndarray]):
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"All columns must share dim 0, got {lengths}")
        self.columns = {k: np.ascontiguousarray(v) for k, v in columns.items()}
        self.length = next(iter(lengths.values()))

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {k: v[i] for k, v in self.columns.items()}


class _Ticket:
    __slots__ = ("ticket", "out", "indices_ref")

    def __init__(self, ticket, out, indices_ref):
        self.ticket = ticket
        self.out = out
        self.indices_ref = indices_ref  # keep the index buffer alive until wait()


class NativeGatherPool:
    """Thread-pool batch assembler over an ArrayDataset (or dict of columns)."""

    def __init__(self, num_threads: int = 4):
        import os

        from . import load_library

        self.lib = load_library()
        self._pool = None
        if self.lib is not None:
            # Gather is memcpy-bound: workers beyond the core count only add
            # context switches (notably in 1-vCPU CI containers).
            num_threads = max(1, min(int(num_threads), os.cpu_count() or 1))
            self._pool = self.lib.atl_pool_create(num_threads)

    @property
    def native(self) -> bool:
        return self._pool is not None

    def close(self):
        if self._pool is not None:
            self.lib.atl_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- synchronous --------------------------------------------------------------
    def gather(self, columns: Dict[str, np.ndarray], indices: Sequence[int]) -> Dict[str, np.ndarray]:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        if not self.native:
            return {k: v[idx] for k, v in columns.items()}
        out = {
            k: np.empty((len(idx),) + v.shape[1:], dtype=v.dtype) for k, v in columns.items()
        }
        t = self._submit(columns, idx, out)
        self.lib.atl_wait(self._pool, t.ticket)
        return out

    # -- async double buffering -----------------------------------------------------
    def submit(self, columns: Dict[str, np.ndarray], indices: Sequence[int]) -> _Ticket:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        if not self.native:
            return _Ticket(None, {k: v[idx] for k, v in columns.items()}, idx)
        out = {
            k: np.empty((len(idx),) + v.shape[1:], dtype=v.dtype) for k, v in columns.items()
        }
        return self._submit(columns, idx, out)

    def wait(self, ticket: _Ticket) -> Dict[str, np.ndarray]:
        if ticket.ticket is not None:
            self.lib.atl_wait(self._pool, ticket.ticket)
        return ticket.out

    def _submit(self, columns: Dict[str, np.ndarray], idx: np.ndarray, out: Dict[str, np.ndarray]) -> _Ticket:
        keys = list(columns.keys())
        n_cols = len(keys)
        srcs = (ctypes.c_void_p * n_cols)()
        dsts = (ctypes.c_void_p * n_cols)()
        row_bytes = (ctypes.c_int64 * n_cols)()
        for i, k in enumerate(keys):
            col = columns[k]
            if not col.flags["C_CONTIGUOUS"]:
                raise ValueError(f"Column {k!r} must be C-contiguous")
            srcs[i] = col.ctypes.data_as(ctypes.c_void_p)
            dsts[i] = out[k].ctypes.data_as(ctypes.c_void_p)
            row_bytes[i] = col.strides[0] if col.ndim > 0 else col.itemsize
        ticket = self.lib.atl_gather_submit(
            self._pool,
            srcs,
            row_bytes,
            n_cols,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx),
            dsts,
        )
        return _Ticket(ticket, out, idx)


def iter_gather_batches(pool: NativeGatherPool, columns: Dict[str, np.ndarray], batch_sampler):
    """Double-buffered batch stream: gather batch N+1 on the pool while N is
    consumed. The finally clause is load-bearing: if the consumer abandons the
    iterator mid-epoch (early `break` → GeneratorExit), the in-flight ticket must
    be waited before its destination buffers are garbage-collected, or the C++
    threads would keep memcpy-ing into freed memory."""
    pending = None
    try:
        for batch_indices in batch_sampler:
            ticket = pool.submit(columns, list(batch_indices))
            if pending is not None:
                yield pool.wait(pending)
            pending = ticket
        if pending is not None:
            yield pool.wait(pending)
            pending = None
    finally:
        if pending is not None:
            pool.wait(pending)


class NativeArrayLoader:
    """SimpleDataLoader-shaped iterator: ArrayDataset + batch sampler, batches
    assembled natively one step ahead (the C++ analogue of torch's worker pool)."""

    def __init__(self, dataset: ArrayDataset, batch_sampler, num_threads: int = 4):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.num_threads = num_threads  # kept so prepare()'s sharded rebuild preserves the tuning
        self.pool = NativeGatherPool(num_threads)
        self.collate_fn = None  # parity attribute; collation IS the gather

    def __len__(self):
        return len(self.batch_sampler)

    def set_epoch(self, epoch: int):
        """Loader-surface parity with SimpleDataLoader/DataLoaderShard: forward
        to an epoch-aware index sampler (SeedableRandomSampler) if one backs
        the batch sampler; a fixed index list has nothing to reshuffle."""
        sampler = getattr(self.batch_sampler, "sampler", None)
        if hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)

    def __iter__(self):
        yield from iter_gather_batches(self.pool, self.dataset.columns, self.batch_sampler)

"""Continuous-batching serving engine: slot-based in-flight batching over the
fused decode loop.

The static `Generator` path runs one prefill + one fused decode to completion:
short requests wait for the longest row, finished rows burn MXU cycles on masked
work, and nothing new can join until the whole batch drains. `ContinuousBatcher`
keeps the GSPMD single-compiled-program discipline (one decode executable, ever)
but makes the BATCH dynamic at the host level:

  - A fixed-capacity **slot batch**: `num_slots` rows over one static KV cache.
    A slot is a logical cache row; requests come and go, the compiled program
    never changes shape. By default (`paged=True`) the cache is a POOL of
    fixed-size KV pages plus per-slot page tables riding as traced int32
    operands (`ops/attention.update_slot_cache` paged mode): admission reserves
    `ceil((prompt + max_new) / page_size)` pages — memory proportional to each
    request's ACTUAL footprint, not the engine-wide `max_length` worst case —
    and a page-granular prefix cache (`paging.PagePool`) maps shared prompt
    prefixes (system prompts) to shared read-only pages with refcounts, so a
    repeated prefix costs zero prefill FLOPs and zero duplicate HBM after its
    first request. `paged=False` keeps the dense one-row-per-slot layout;
    greedy decode is token-identical between the two.
  - **insert** (one executable per power-of-two prompt bucket): prefill a new
    request's prompt through the ordinary decode-cache path on a batch-1 cache,
    then `tree_scatter_rows` it into the free slot's cache rows, read the logits
    at the prompt's REAL length (a traced scalar — bucket pads never recompile),
    and sample the first token. TTFT = one insert dispatch.
  - **decode_chunk** (ONE executable per engine): a `lax.scan` stepping ALL
    slots `chunk_size` tokens per dispatch through the models' per-row slot
    cache (`ops/attention.update_slot_cache`). Per-slot position counters,
    per-slot GenerationConfig scalars (temperature / repetition penalty / EOS id
    / token budget ride as traced operands, the no-recompile discipline of
    generation.py's fused loop), EOS + budget masking, and a packed
    `(slot_id, token)` output buffer the host drains for streaming.

  - **speculative decode** (`speculative=True`): each chunk iteration becomes
    a draft-then-verify step — a host-free n-gram drafter
    (`speculative.propose_ngram_drafts`) proposes `draft_tokens` continuations
    from the slot's own observed context, ONE multi-token verify dispatch
    (`make_causal_programs(..., verify_block=True)` over
    `update_slot_cache`'s multi-position path) scores all of them, and the
    longest greedily-confirmed prefix plus one bonus token is emitted — 1 to
    draft_tokens+1 tokens per dispatch instead of exactly 1, with greedy
    output token-identical to the plain path by construction. The accept/
    reject loop, EOS-in-block truncation, and history maintenance are all
    traced ops inside the one decode executable; the host only pushes its
    [S, max_length] context mirror as one more per-dispatch operand. Greedy
    engines only (sampling/repetition-penalty engines raise); paged admission
    reserves the draft window's pages alongside the request footprint.

Between chunks the host frees finished slots and admits queued requests — a
late-arriving request starts decoding while earlier long requests are still
mid-flight. Stale K/V from a slot's previous occupant is never visible: each row
attends only `cols <= its own position`, and insert overwrites the prompt rows.

Greedy outputs are token-identical to the static `Generator` path (pads
contribute exact zeros under the f32 softmax; rows are independent in every
layer), which is what `tests/test_serving.py` pins.

Fault isolation (the serving-runtime half of the resilience layer): the engine
degrades PER-REQUEST, never per-process. Admission failures (a transient device
error during an insert, a malformed prompt that slipped validation) mark only
that request `finish_reason="error"`; per-request wall-clock deadlines are
enforced at step boundaries (`finish_reason="timeout"`); `cancel()` frees an
in-flight slot immediately; a bounded queue raises `QueueFull` so callers get
explicit backpressure instead of unbounded host memory growth; and
`drain()`/`close()` give the server a clean shutdown lifecycle. The one shared
decode executable is the blast-radius exception: if a chunk dispatch itself
dies, every in-flight request errors (the cache state is gone) but the engine
stays up and keeps admitting — the slot cache is rebuilt from zeros, since the
failed dispatch may already have consumed the donated buffers. An insert
failure that consumed ITS donated operands (accelerators only) widens to the
same blast-radius recovery; otherwise admission failures stay per-request.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .generation import (
    GenerationConfig,
    _apply_repetition_penalty,
    _bucket_for,
    _operand,
    _params_resolver,
    _sample,
    make_cached_prefill_program,
    make_causal_programs,
)
from .logging import get_logger
from .paging import SCRATCH_PAGE, PagePool, chain_hashes, pages_for
from .parallel.sharding import constrain_tp_cache, tree_device_nbytes
from .speculative import (
    DEFAULT_DRAFT_NGRAM,
    DEFAULT_DRAFT_TOKENS,
    greedy_accept_length,
    propose_ngram_drafts,
)
from .telemetry import MetricsRegistry
from .telemetry.tracing import default_tracer
from .utils.operations import (
    tree_gather_pages,
    tree_scatter_pages,
    tree_scatter_rows,
    tree_zero_cache_tail,
)

logger = get_logger(__name__)


class QueueFull(RuntimeError):
    """Bounded-queue backpressure: the engine's wait queue is at `max_queue`.
    Callers shed load (HTTP 429 / retry-after) instead of growing host memory."""


class EngineClosed(RuntimeError):
    """The engine was `close()`d (or is mid-`drain()`) and takes no new work."""


#: Every value `RequestResult.finish_reason` can take.
FINISH_REASONS = ("eos", "length", "timeout", "error", "cancelled")


@dataclass
class Request:
    """One serving request. `eos_token_id`, `max_new_tokens`, `temperature` and
    `repetition_penalty` are PER-REQUEST (traced operands of the shared decode
    program); `do_sample`/`top_k`/`top_p` are engine-level (they shape the
    compiled sampler, exactly as in `Generator._decode_fn`).

    `deadline_s` is a wall-clock budget in seconds measured from `submit()`;
    enforced at step boundaries, so a request can overrun by at most one chunk
    before finishing with `finish_reason="timeout"` (partial tokens kept).

    `tenant` and `priority` are ROUTER-level admission-control fields
    (`router.Router(tenant_queue_limit=...)`): the engine itself ignores them —
    a single engine is one queue — but carries them so requests survive
    `dataclasses.replace` round trips through the fleet layers."""

    request_id: int
    input_ids: Any  # [prompt_len] int sequence
    max_new_tokens: int = 32
    temperature: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0  # caller-defined clock, echoed into the result
    deadline_s: Optional[float] = None  # wall-clock budget from submit; None = no deadline
    tenant: Optional[str] = None  # admission-control class (router fair share)
    priority: int = 0  # higher dispatches first across tenant queues (router)


@dataclass
class RequestResult:
    request_id: int
    tokens: List[int] = field(default_factory=list)
    arrival_time: float = 0.0
    first_token_time: Optional[float] = None  # host perf_counter at insert return
    finish_time: Optional[float] = None
    finished: bool = False
    finish_reason: Optional[str] = None  # one of FINISH_REASONS
    error: Optional[str] = None  # repr of the exception when finish_reason == "error"


class ContinuousBatcher:
    """Slot-based in-flight batching over the fused decode loop.

    Typical driving loop::

        engine = ContinuousBatcher(model, num_slots=8, chunk_size=16)
        for r in requests:
            engine.submit(r)
        while engine.pending:
            for request_id, new_tokens in engine.step():
                stream(request_id, new_tokens)   # incremental drain

    `step()` = admit-into-free-slots, dispatch ONE decode chunk, drain the packed
    stream buffer. The decode executable is compiled exactly once per
    (num_slots, chunk_size, sampler shape); admission compiles one insert
    executable per power-of-two prompt bucket and never touches the decode
    program (`trace_counts` proves it).
    """

    def __init__(
        self,
        model,
        num_slots: int = 4,
        max_length: Optional[int] = None,
        chunk_size: int = 8,
        do_sample: bool = False,
        top_k: int = 0,
        top_p: float = 1.0,
        use_repetition_penalty: bool = False,
        rng=None,
        max_queue: Optional[int] = None,
        trace_guard=None,
        registry: Optional[MetricsRegistry] = None,
        tracer=None,
        paged: bool = True,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
        speculative: bool = False,
        draft_tokens: int = DEFAULT_DRAFT_TOKENS,
        draft_ngram: int = DEFAULT_DRAFT_NGRAM,
        attention_impl: str = "xla",
        weight_dtype: str = "bf16",
        kv_cache_dtype: str = "bf16",
        tp: int = 1,
        tp_devices=None,
        tp_group: int = 0,
        sharding_rules: Any = None,
        sharding_refine_top_k: int = 0,
    ):
        if getattr(model, "module", None) is None or not hasattr(model.module, "config"):
            raise ValueError("ContinuousBatcher needs a Model bundle built from an in-tree flax module")
        base = model.module.config
        if not hasattr(base, "decode_slot_cache"):
            raise ValueError(
                f"{type(model.module).__name__}'s config has no `decode_slot_cache` "
                "field — this model family doesn't support slot-batched serving yet"
            )
        if paged and not hasattr(base, "decode_page_size"):
            raise ValueError(
                f"{type(model.module).__name__}'s config has no `decode_page_size` "
                "field — this model family doesn't support the paged KV cache; "
                "pass paged=False for the contiguous per-slot layout"
            )
        self.base_config = base
        # Quantized serving (ops/quantization.py): `weight_dtype="int8"`
        # quantizes the params ONCE at load/swap time (the `params` setter
        # below) and routes every Dense through the int8-epilogue matmul;
        # `kv_cache_dtype` picks the paged pool's storage dtype, with
        # per-page-per-head scales riding the cache collection as traced
        # operands. Both are static config — dtypes never retrace.
        from .ops.quantization import KV_CACHE_DTYPES, WEIGHT_DTYPES

        self.weight_dtype = str(weight_dtype)
        if self.weight_dtype not in WEIGHT_DTYPES:
            raise ValueError(
                f"unknown weight_dtype {weight_dtype!r}; expected one of {WEIGHT_DTYPES}"
            )
        self.kv_cache_dtype = str(kv_cache_dtype)
        if self.kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"unknown kv_cache_dtype {kv_cache_dtype!r}; expected one of {KV_CACHE_DTYPES}"
            )
        if self.kv_cache_dtype != "bf16" and not paged:
            raise ValueError(
                "a quantized KV cache requires the paged layout (paged=True): "
                "the per-page-per-head scale pools have no contiguous twin"
            )
        # Tensor-parallel decode: one engine spanning a `tp`-device submesh
        # whose single "model" axis carries the model family's Megatron
        # column/row-parallel rules (parallel/sharding.py). Weights, the KV
        # pool (by KV head) and the quantized scale pools are placed sharded;
        # GSPMD inserts the collectives into the SAME one-decode-executable
        # programs — page tables, sampling scalars and token operands stay
        # replicated host pushes, so admissions still never recompile. tp=1
        # is byte-for-byte the single-device engine (mesh is None).
        self.tp = int(tp)
        if self.tp < 1:
            raise ValueError("tp must be >= 1")
        self.mesh = None
        self._param_shardings = None
        self._cache_shardings = None
        # Sharding-rule source: None / "rules" -> the model family's
        # hand-written table (the parity oracle); "auto" -> the cost-model
        # planner (parallel/planner.py) searches the layout from shapes +
        # mesh topology and emits an equivalent table; an explicit list is a
        # caller override. The planner call itself happens below, once the
        # paged-pool geometry it prices is known.
        self.sharding_mode = "rules" if sharding_rules is None else sharding_rules
        if isinstance(self.sharding_mode, (list, tuple)):
            self._tp_rules = list(self.sharding_mode)
            self.sharding_mode = "explicit"
        elif self.sharding_mode in ("rules", "auto"):
            self._tp_rules = list(getattr(model, "sharding_rules", None) or [])
        else:
            raise ValueError(
                f"sharding_rules must be a rules list, None, 'rules' or 'auto'; "
                f"got {sharding_rules!r}"
            )
        self.sharding_plan = None
        self.sharding_refine_top_k = int(sharding_refine_top_k)
        if self.tp > 1:
            from .parallel.sharding import serving_tp_mesh

            if not self._tp_rules and self.sharding_mode != "auto":
                raise ValueError(
                    f"{type(model.module).__name__}'s Model bundle carries no "
                    "sharding_rules — this model family has no Megatron TP "
                    "layout to span a mesh with; pass tp=1 or "
                    "sharding_rules=\"auto\" to let the planner derive one"
                )
            kv_heads = getattr(base, "num_key_value_heads", base.num_attention_heads)
            if kv_heads % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide the model's KV head count "
                    f"({kv_heads}): the KV pool shards by KV head over the "
                    "\"model\" axis"
                )
            self.mesh = serving_tp_mesh(self.tp, devices=tp_devices, group=tp_group)
        self.num_slots = int(num_slots)
        self.max_length = int(max_length or base.max_position_embeddings)
        self.chunk_size = int(chunk_size)
        self.do_sample = do_sample
        self.top_k = top_k
        self.top_p = top_p
        self.use_repetition_penalty = use_repetition_penalty
        if self.num_slots < 1 or self.chunk_size < 1:
            raise ValueError("num_slots and chunk_size must be >= 1")
        self.speculative = bool(speculative)
        self.draft_tokens = int(draft_tokens)
        self.draft_ngram = int(draft_ngram)
        if self.speculative:
            if self.draft_tokens < 1 or self.draft_ngram < 1:
                raise ValueError("speculative decode needs draft_tokens >= 1 and draft_ngram >= 1")
            if do_sample:
                raise ValueError(
                    "speculative decode is greedy-only: draft verification accepts "
                    "argmax matches, which is not distribution-preserving under "
                    "sampling — pass do_sample=False or speculative=False"
                )
            if use_repetition_penalty:
                raise ValueError(
                    "speculative decode does not compose with use_repetition_penalty "
                    "(the presence update is order-dependent across a verified "
                    "block); disable one of the two"
                )
        # Decode/verify attention implementation: "xla" keeps the gather-then-
        # attend oracle; "pallas_paged" fuses the page-table walk into the
        # ops/paged_attention kernels (paged engines only). Either way the ONE
        # decode executable and the traced-operand page tables are unchanged —
        # the impl only swaps the attention read inside the compiled program.
        from .ops.attention import SLOT_ATTENTION_IMPLS

        self.attention_impl = str(attention_impl)
        if self.attention_impl not in SLOT_ATTENTION_IMPLS:
            raise ValueError(
                f"unknown attention_impl {attention_impl!r}; expected one of "
                f"{SLOT_ATTENTION_IMPLS}"
            )
        if self.attention_impl == "pallas_paged" and not paged:
            raise ValueError(
                "attention_impl='pallas_paged' requires the paged KV cache "
                "(paged=True); the contiguous layout has no page table to walk"
            )
        self.paged = bool(paged)
        self.page_size = int(page_size)
        if self.paged:
            if self.page_size < 1:
                raise ValueError("page_size must be >= 1")
            self.pages_per_slot = -(-self.max_length // self.page_size)
            # Per-slot logical capacity rounded up to whole pages; columns past
            # max_length stay masked (exact zeros under the f32 softmax), so
            # decode is token-identical to the contiguous layout.
            self._padded_length = self.pages_per_slot * self.page_size
            # Default pool: the contiguous layout's worst case (every slot at
            # max_length) plus the scratch page — same capacity, so admission
            # only ever gets LOOSER. Size it DOWN for real HBM savings: any
            # request mix whose actual token footprint fits still completes.
            self.num_pages = (
                int(num_pages) if num_pages is not None
                else self.num_slots * self.pages_per_slot + 1
            )
        else:
            self.pages_per_slot = 0
            self._padded_length = self.max_length
            self.num_pages = 0
        # Prefix sharing needs the suffix-only insert to seed presence from the
        # WHOLE prompt, which the suffix program never sees — repetition-penalty
        # engines therefore run the paged cache without prefix reuse.
        self.use_prefix_cache = bool(prefix_cache) and self.paged and not use_repetition_penalty
        if prefix_cache and self.paged and use_repetition_penalty:
            logger.info(
                "prefix cache disabled: use_repetition_penalty needs whole-prompt "
                "presence seeding, which shared-prefix inserts cannot provide"
            )

        params_tree = model.params if "params" in model.params else {"params": model.params}
        if self.tp > 1 and self.sharding_mode == "auto":
            # The planner searches the Megatron layout from shapes + mesh
            # topology, pricing the KV pool at the live cache dtype, and
            # emits a table the SAME derivation below consumes — swap-in
            # weights, cache init and the TPU118 audit all behave exactly as
            # with a hand table. With sharding_refine_top_k > 1, the top-k
            # candidates are compiled as one-token forwards and the
            # measured-best wins (cost model proposes, hardware disposes).
            from .parallel.planner import (
                measure_forward_step,
                plan_serving_sharding,
                refine_plans,
            )

            top_k = max(1, self.sharding_refine_top_k)
            planned = plan_serving_sharding(
                params_tree,
                self.mesh,
                base,
                num_slots=self.num_slots,
                padded_length=self._padded_length,
                paged=self.paged,
                page_size=self.page_size,
                num_pages=self.num_pages,
                kv_cache_dtype=self.kv_cache_dtype,
                weight_dtype=self.weight_dtype,
                top_k=top_k,
            )
            if self.sharding_refine_top_k >= 1:
                # refine_top_k=1 still measures: the single candidate gets a
                # real compiled-forward timing stamped on measured_step_s.
                best, _ = refine_plans(
                    planned if isinstance(planned, list) else [planned],
                    lambda plan: measure_forward_step(
                        model.apply_fn, params_tree, self.mesh, plan.rules, batch=1
                    ),
                )
                self.sharding_plan = best
            else:
                self.sharding_plan = planned
            self._tp_rules = list(self.sharding_plan.rules)

        self.params = params_tree
        resolve = _params_resolver(model)
        # Prefill rides the ORDINARY decode-cache path on a batch-1 cache (shared
        # scalar cache_index); decode steps ride the per-row slot cache. Same
        # logical cache capacity so the prefilled rows line up for the scatter —
        # into slot rows (contiguous) or pool pages (paged).
        cache_len = self._padded_length
        quant_cfg = {}
        if self.weight_dtype != "bf16":
            if not hasattr(base, "weight_dtype"):
                raise ValueError(
                    f"{type(model.module).__name__}'s config has no `weight_dtype` "
                    "field — this model family doesn't support int8 weight-only "
                    "serving yet"
                )
            quant_cfg["weight_dtype"] = self.weight_dtype
        prefill_cfg = dataclasses.replace(base, decode_cache_length=cache_len, **quant_cfg)
        if self.mesh is not None:
            # The slot-decode modules carry the submesh so the Pallas page-walk
            # kernels can shard_map over the KV-head grid; prefill stays
            # mesh-free in config (its XLA paths partition off the sharded
            # operands alone).
            if not hasattr(base, "decode_tp_mesh"):
                raise ValueError(
                    f"{type(model.module).__name__}'s config has no "
                    "`decode_tp_mesh` field — this model family doesn't "
                    "support tensor-parallel serving yet"
                )
            quant_cfg["decode_tp_mesh"] = self.mesh
        if self.paged:
            if self.kv_cache_dtype != "bf16":
                if not hasattr(base, "decode_kv_cache_dtype"):
                    raise ValueError(
                        f"{type(model.module).__name__}'s config has no "
                        "`decode_kv_cache_dtype` field — this model family doesn't "
                        "support the quantized KV page pool yet"
                    )
                quant_cfg["decode_kv_cache_dtype"] = self.kv_cache_dtype
            step_cfg = dataclasses.replace(
                base, decode_cache_length=cache_len, decode_slot_cache=True,
                decode_page_size=self.page_size, decode_num_pages=self.num_pages,
                decode_attention_impl=self.attention_impl, **quant_cfg,
            )
        else:
            step_cfg = dataclasses.replace(
                base, decode_cache_length=cache_len, decode_slot_cache=True, **quant_cfg
            )
        prefill_module = type(model.module)(prefill_cfg)
        step_module = type(model.module)(step_cfg)
        self._prefill_raw, _ = make_causal_programs(prefill_module, resolve, full_prefill_logits=True)
        _, self._step_raw, self._verify_raw = make_causal_programs(
            step_module, resolve, step_mask_operand=self.paged, verify_block=True
        )
        self._step_module = step_module
        self._resolve = resolve
        if self.paged:
            self._cached_prefill_raw = make_cached_prefill_program(prefill_module, resolve)
            # The dense batch-1 cache STRUCTURE the paged insert materializes by
            # gathering pool pages (zero compute/compile: eval_shape only). The
            # weight_autocast wrap matters even for eval_shape: int8 engines
            # hold quantized kernel entries the raw Dense can't consume.
            from .ops.quantization import weight_autocast

            dummy = jnp.zeros((1, 1), jnp.int32)
            dpos = jnp.zeros((1, 1), jnp.int32)
            with weight_autocast(self.weight_dtype):
                self._dense_cache_struct = jax.eval_shape(
                    lambda p: prefill_module.apply(resolve(p), dummy, None, dpos, mutable=["cache"])[1]["cache"],
                    self.params,
                )

        self._sample_config = GenerationConfig(do_sample=do_sample, top_k=top_k, top_p=top_p)
        # Python-side effects run at TRACE time: these count compiles, and the
        # serving tests pin "decode compiled once across mixed admissions" on them.
        self.trace_counts: Dict[str, int] = {"insert": 0, "decode_chunk": 0}

        self._rng = rng if rng is not None else jax.random.key(0)
        self._insert_fns: Dict[int, Any] = {}
        self._chunk_fn = self._build_spec_chunk() if self.speculative else self._build_chunk()
        self._cache = self._init_cache()
        self._presence = (
            jnp.zeros((self.num_slots, base.vocab_size), bool) if use_repetition_penalty else None
        )
        if self.mesh is not None:
            # Commit the carried device state (rng; presence when penalized)
            # REPLICATED on the submesh up front: these thread through every
            # dispatch, and an uncommitted first-call signature followed by a
            # committed second-call one would recompile the one decode
            # executable the engine promises never to.
            from jax.sharding import NamedSharding, PartitionSpec

            replicated = NamedSharding(self.mesh, PartitionSpec())
            self._rng = jax.device_put(self._rng, replicated)
            if self._presence is not None:
                self._presence = jax.device_put(self._presence, replicated)

        S = self.num_slots
        # Host mirror of the per-slot device operands (small [S] vectors, pushed
        # each dispatch; the CACHE and presence stay device-resident/donated).
        self._token = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._rem = np.zeros(S, np.int32)
        self._eos = np.full(S, -1, np.int32)
        self._temp = np.ones(S, np.float32)
        self._pen = np.ones(S, np.float32)
        # Per-slot page tables (paged): all-zeros rows point at the scratch
        # page, so a freed/inactive slot's discarded decode writes can never
        # land in a live request's pages. Contiguous engines keep a [S, 1]
        # dummy so the chunk signature stays uniform (the operand is unused).
        self._page_table = np.zeros((S, self.pages_per_slot if self.paged else 1), np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(S)]
        # Speculative engines: host mirror of each slot's observed context
        # (prompt + generated, packed from index 0), pushed as a traced operand
        # each chunk dispatch — the same mirror discipline as _token/_pos. The
        # device updates its copy inside the scan (drafts must see tokens
        # emitted earlier in the SAME chunk); the host re-derives identical
        # content from the drained stream, so nothing is ever read back.
        self._history = np.zeros((S, self.max_length if self.speculative else 1), np.int32)

        self._slot_request: List[Optional[RequestResult]] = [None] * S
        self._queue: deque = deque()
        self.results: Dict[int, RequestResult] = {}
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None for unbounded)")
        self._deadlines: Dict[int, float] = {}  # request_id -> absolute perf_counter deadline
        self._closed = False
        self._draining = False
        # Optional analysis.TraceGuard (assignable after construction too): the
        # engine's fault isolation swallows per-step exceptions, so guarded
        # transfer violations are `observe()`d before being isolated — the
        # analysis ledger sees them even though serving keeps running.
        self.trace_guard = trace_guard
        # Telemetry: every health counter lives in a MetricsRegistry (shareable
        # with the Accelerator's, exportable via telemetry.export); the public
        # `stats` dict is now a read-only VIEW over these instruments. All
        # updates are host-scalar arithmetic — nothing here syncs the device.
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._m_submitted = self.metrics.counter(
            "serving_requests_submitted_total", help="requests accepted by submit()"
        )
        self._m_inserts = self.metrics.counter(
            "serving_inserts_total", help="successful insert (prefill+admit) dispatches"
        )
        self._m_chunks = self.metrics.counter(
            "serving_chunks_total", help="decode-chunk dispatches"
        )
        self._m_decode_steps = self.metrics.counter(
            "serving_decode_steps_total", help="decode loop iterations (chunks * chunk_size)"
        )
        self._m_finish = {
            reason: self.metrics.counter(
                "serving_requests_finished_total",
                help="finished requests by finish_reason",
                labels={"reason": reason},
            )
            for reason in FINISH_REASONS
        }
        self._m_queue_depth = self.metrics.gauge(
            "serving_queue_depth", help="requests waiting for a slot"
        )
        self._m_queue_peak = self.metrics.gauge(
            "serving_queue_peak",
            help="queue-depth high-water mark (sized against max_queue)",
        )
        self._m_slots_in_use = self.metrics.gauge(
            "serving_slots_in_use", help="slots occupied by in-flight requests"
        )
        self._m_slot_utilization = self.metrics.gauge(
            "serving_slot_utilization", help="slots_in_use / num_slots"
        )
        self._m_ttft = self.metrics.histogram(
            "serving_ttft_seconds", help="submit() -> first token (host wall clock)"
        )
        self._m_inter_token = self.metrics.histogram(
            "serving_inter_token_seconds",
            help="per-token gap between stream drains for an in-flight slot",
        )
        self._m_chunk_latency = self.metrics.histogram(
            "serving_chunk_seconds", help="decode-chunk dispatch+drain wall clock"
        )
        self._submit_times: Dict[int, float] = {}  # request_id -> submit() perf_counter
        self._slot_last_event = np.zeros(S, np.float64)  # last drain time per slot

        # Request-scoped tracing (telemetry.tracing): one `serve.request` span
        # per accepted request from submit() to its terminal finish_reason,
        # child `serve.insert` spans per admission dispatch, and batched
        # `serve.decode_chunk` spans with slot annotations. Everything is
        # host-clock arithmetic — the spans ride the same zero-device-sync
        # discipline as the metrics (and TPU112 lints the annotations).
        self.tracer = tracer if tracer is not None else default_tracer()
        self._request_spans: Dict[int, Any] = {}

        # Page-pool + prefix-cache telemetry and the host allocator itself
        # (paged engines only; all updates are host-scalar arithmetic).
        self.pool: Optional[PagePool] = None
        if self.paged:
            self._m_pages_total = self.metrics.gauge(
                "serving_pages_total", help="usable KV pool pages (excludes the scratch page)"
            )
            self._m_pages_in_use = self.metrics.gauge(
                "serving_pages_in_use", help="pool pages referenced by in-flight requests"
            )
            self._m_prefix_hits = self.metrics.counter(
                "serving_prefix_cache_hits_total",
                help="prompt pages served from the shared-prefix cache",
            )
            self._m_prefix_misses = self.metrics.counter(
                "serving_prefix_cache_misses_total",
                help="full prompt pages that had to be prefilled (no cached prefix)",
            )
            self._m_prefix_evictions = self.metrics.counter(
                "serving_prefix_cache_evictions_total",
                help="unreferenced cached prefix pages reclaimed by the allocator",
            )
            self._m_prefill_saved = self.metrics.counter(
                "prefill_tokens_saved_total",
                help="prompt tokens whose prefill FLOPs the prefix cache skipped",
            )
            self.pool = PagePool(
                self.num_pages, self.page_size,
                on_evict=self._m_prefix_evictions.inc,
                kv_cache_dtype=self.kv_cache_dtype,
            )
            self._m_pages_total.set(self.pool.pages_total)

        # Speculative-decode telemetry (host-scalar arithmetic over the chunk
        # readback; docs/observability.md documents the instruments). The
        # headline derived number — accepted_tokens_per_step — is
        # (verify_steps + accepted) / verify_steps, surfaced in `stats`.
        if self.speculative:
            self._m_spec_steps = self.metrics.counter(
                "serving_spec_verify_steps_total",
                help="verify-block loop iterations with an active slot (each emits >= 1 token)",
            )
            self._m_spec_drafted = self.metrics.counter(
                "serving_spec_draft_tokens_total",
                help="draft tokens proposed by the n-gram drafter (valid proposals only)",
            )
            self._m_spec_accepted = self.metrics.counter(
                "serving_spec_accepted_draft_tokens_total",
                help="draft tokens confirmed by verification and emitted",
            )
            self._m_spec_rejected = self.metrics.counter(
                "serving_spec_rejected_draft_tokens_total",
                help="draft tokens the verify step discarded",
            )
            self._m_spec_hist = self.metrics.histogram(
                "serving_spec_accepted_tokens",
                help="tokens emitted per verify step (accepted drafts + 1 bonus)",
                buckets=[float(i) for i in range(1, self.draft_tokens + 2)],
            )

    # ------------------------------------------------------------------ programs

    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, value):
        """The weight-load seam: construction, the router's rolling
        `swap_weights`, the ReplicaSet rebuild path, and the worker's
        `set_params` op all assign here. int8 engines quantize per-output-
        channel scales ONCE per assignment (`quantize_params_int8` —
        idempotent, so an already-quantized tree passes through), which is
        exactly the "scales computed at weight-load/swap time" contract: the
        compiled programs only ever see int8 kernels + scale operands.

        Tensor-parallel engines RE-SHARD here too: the (possibly quantized)
        tree is `device_put` onto the submesh with the model family's
        Megatron rules (`derive_tp_param_shardings` — quantized {"q",
        "scale"} entries ride their kernel's rule), so a rolling
        `swap_weights` lands already-sharded weights with zero recompiles
        and an already-placed tree passes through as the same buffers."""
        if self.weight_dtype == "int8":
            from .ops.quantization import quantize_params_int8

            value = quantize_params_int8(value)
        if self.mesh is not None:
            from .parallel.sharding import derive_tp_param_shardings

            self._param_shardings = derive_tp_param_shardings(
                value, self.mesh, self._tp_rules
            )
            value = jax.device_put(value, self._param_shardings)
        self._params = value

    def _init_cache(self):
        """Create the slot cache — dense [num_slots, max_length] rows, or the
        [num_pages, page_size] pool when paged (quantized dtypes add the
        per-page-per-head scale pools): `eval_shape` the slot-mode
        module's cache variables (zero compute, zero compile — no throwaway
        executable at engine construction) and materialize them as zeros.
        Correct because every slot's rows/pages are overwritten by insert
        before they're ever attended."""
        from .ops.quantization import weight_autocast

        S = self.num_slots
        module, resolve = self._step_module, self._resolve
        dummy = jnp.zeros((S, 1), jnp.int32)
        pos = jnp.zeros((S, 1), jnp.int32)
        mask = jnp.zeros((S, self.pages_per_slot), jnp.int32) if self.paged else None
        with weight_autocast(self.weight_dtype):
            shapes = jax.eval_shape(
                lambda p: module.apply(resolve(p), dummy, mask, pos, mutable=["cache"])[1]["cache"],
                self.params,
            )
        cache = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self.mesh is not None:
            # Place the pools SHARDED by KV head over the submesh (scale
            # pools by head, scalars replicated) — blast-radius rebuilds come
            # through here too, so recovery reconstructs the sharded layout.
            from .parallel.sharding import derive_tp_cache_shardings

            self._cache_shardings = derive_tp_cache_shardings(cache, self.mesh)
            cache = jax.device_put(cache, self._cache_shardings)
        return cache

    @staticmethod
    def plan_admission_bucket(
        p: int, matched_pages: int, page_size: int, padded_length: int
    ) -> Tuple[int, int]:
        """Pure admission planner: (insert bucket, matched pages to KEEP) for a
        `p`-token prompt with `matched_pages` prefix-cache hits.

        The bucket set this can return is CLOSED — powers of two (prefix-hit
        suffixes floored at the page-size bucket, so a deepening cache never
        mints ever-smaller buckets) plus the single capped value
        `padded_length` (a full-window prompt with no prefix hit). A pow2
        suffix bucket that would overflow the cache window (`matched_len +
        bucket > padded_length`) DROPS trailing matched pages until it fits
        instead of shrinking the bucket to a matched_len-dependent remainder:
        an open set of remainder-sized buckets is exactly what used to compile
        a fresh insert executable on the first deep prefix hit of a timed run.
        `warm_inserts` precompiles the whole closed set."""
        floor_bucket = _bucket_for(page_size)
        matched_len = matched_pages * page_size
        while matched_pages and (
            matched_len + max(_bucket_for(p - matched_len), floor_bucket) > padded_length
        ):
            matched_pages -= 1
            matched_len -= page_size
        bucket = _bucket_for(p - matched_len)
        if matched_pages:
            bucket = max(bucket, floor_bucket)
        # Only binds when matched_pages == 0: the single fixed top bucket.
        bucket = min(bucket, padded_length - matched_len)
        return bucket, matched_pages

    def insert_bucket_ladder(self) -> List[int]:
        """Every insert bucket any admission of this engine can mint: the pow2
        ladder below the cache window plus the capped top value. Closed by
        `plan_admission_bucket` (paged) / the `min(bucket, max_length)` cap
        (contiguous)."""
        limit = self._padded_length if self.paged else self.max_length
        ladder = []
        b = 1
        while b < limit:
            ladder.append(b)
            b <<= 1
        ladder.append(limit)
        return ladder

    def warm_inserts(self) -> List[int]:
        """Precompile the full insert-bucket ladder so NO admission — whatever
        prompt length or prefix-cache depth it arrives with — compiles at
        serving time. Each warm call donates a THROWAWAY zero cache (never the
        engine's), so engine state is untouched. Returns the buckets warmed.

        Cost: one small compile per ladder rung (log2 of the cache window), a
        few seconds at init; the payoff is a mechanical 0-recompile guarantee
        across the whole admission space instead of 'whatever the warmup
        traffic happened to mint'."""
        import jax

        warmed = []
        for bucket in self.insert_bucket_ladder():
            fn = self._insert_fn(bucket)
            dummy_cache = jax.tree_util.tree_map(jnp.zeros_like, self._cache)
            if self._cache_shardings is not None:
                # Warm with the REAL sharded signature: an unsharded dummy
                # would compile a throwaway executable and the first live
                # admission would still pay the sharded compile.
                dummy_cache = jax.device_put(dummy_cache, self._cache_shardings)
            dummy_presence = (
                jax.tree_util.tree_map(jnp.zeros_like, self._presence)
                if self._presence is not None
                else None
            )
            ids = jnp.zeros((1, bucket), jnp.int32)
            if self.paged:
                fn(
                    self.params, dummy_cache, dummy_presence, ids,
                    _operand(1, np.int32), _operand(0, np.int32), _operand(0, np.int32),
                    jnp.asarray(np.zeros((self.pages_per_slot,), np.int32)),
                    _operand(0, np.int32), _operand(1.0, np.float32),
                    _operand(1.0, np.float32), self._rng,
                )
            else:
                fn(
                    self.params, dummy_cache, dummy_presence, ids,
                    _operand(1, np.int32), _operand(0, np.int32),
                    _operand(1.0, np.float32), _operand(1.0, np.float32), self._rng,
                )
            warmed.append(bucket)
        return warmed

    def _insert_fn(self, bucket: int):
        """One compiled insert per power-of-two prompt bucket (paged: per
        SUFFIX bucket — the unmatched tail after prefix-cache hits). The real
        length, the slot index, page table row, matched prefix length,
        temperature/penalty and the rng all ride as traced operands —
        re-admission never recompiles anything."""
        if self.paged:
            return self._paged_insert_fn(bucket)
        return self._contiguous_insert_fn(bucket)

    def _contiguous_insert_fn(self, bucket: int):
        fn = self._insert_fns.get(bucket)
        if fn is not None:
            return fn
        prefill = self._prefill_raw
        use_pen = self.use_repetition_penalty
        config = self._sample_config
        V = self.base_config.vocab_size
        mesh = self.mesh

        def insert(params, cache, presence, input_ids, real_len, slot, temperature, penalty, rng):
            self.trace_counts["insert"] += 1
            positions = jnp.broadcast_to(jnp.arange(bucket)[None, :], (1, bucket))
            logits, small = prefill(params, input_ids, positions)
            cache = constrain_tp_cache(tree_scatter_rows(cache, small, slot), mesh)
            # Logits at the REAL last prompt token (right-bucket pads sit above
            # it and, being causal, never influenced it).
            last = jax.lax.dynamic_slice_in_dim(logits, real_len - 1, 1, axis=1)[:, 0, :]
            row = None
            if use_pen:
                valid = jnp.arange(bucket) < real_len
                row = jnp.zeros((V,), bool).at[input_ids[0]].max(valid)
                last = _apply_repetition_penalty(last, row[None, :], penalty)
            token, rng = _sample(last, config, rng, temperature)
            if use_pen:
                row = row.at[token[0]].set(True)
                presence = jax.lax.dynamic_update_slice(
                    presence, row[None, :], (jnp.asarray(slot, jnp.int32), jnp.int32(0))
                )
            return token[0], cache, presence, rng

        donate = (1, 2) if use_pen else (1,)
        fn = jax.jit(insert, donate_argnums=donate)
        self._insert_fns[bucket] = fn
        return fn

    def _paged_insert_fn(self, bucket: int):
        """Paged admission: gather the slot's (possibly shared-prefix) pages
        into a batch-1 dense cache positioned at `matched_len`, prefill ONLY the
        unmatched suffix through it, scatter the result back into pool pages —
        with every already-matched table entry redirected to the scratch page,
        so a shared read-only prefix page is never rewritten — and sample the
        first token from the suffix's real last logits. A full prefix hit still
        recomputes the prompt's final token (matching is capped below the whole
        prompt), so first-token logits always exist."""
        fn = self._insert_fns.get(bucket)
        if fn is not None:
            return fn
        cached_prefill = self._cached_prefill_raw
        dense_struct = self._dense_cache_struct
        use_pen = self.use_repetition_penalty
        config = self._sample_config
        V = self.base_config.vocab_size
        P = self.pages_per_slot
        mesh = self.mesh

        def insert(
            params, pool_cache, presence, suffix_ids, real_len, matched_len,
            matched_pages, page_row, slot, temperature, penalty, rng,
        ):
            self.trace_counts["insert"] += 1
            dense = tree_gather_pages(pool_cache, dense_struct, page_row, matched_len)
            positions = matched_len + jnp.broadcast_to(jnp.arange(bucket)[None, :], (1, bucket))
            logits, dense = cached_prefill(params, dense, suffix_ids, positions)
            # Zero rows past the prompt before the write-back: the gather
            # resurrects a recycled page's stale content (never attended, but
            # a QUANTIZED scatter folds it into the boundary page's amax
            # scale, coarsening the real rows; tree_zero_cache_tail).
            dense = tree_zero_cache_tail(dense, matched_len + real_len)
            write_row = jnp.where(
                jnp.arange(P) < matched_pages, jnp.int32(SCRATCH_PAGE), page_row
            )
            pool_cache = constrain_tp_cache(
                tree_scatter_pages(pool_cache, dense, write_row), mesh
            )
            # Logits at the REAL last suffix token (bucket pads sit above it
            # and, being causal, never influenced it).
            last = jax.lax.dynamic_slice_in_dim(logits, real_len - 1, 1, axis=1)[:, 0, :]
            row = None
            if use_pen:
                # Penalty engines run with the prefix cache OFF (matched_len is
                # always 0), so the "suffix" here is the whole prompt and the
                # presence row seeds exactly as on the contiguous path.
                valid = jnp.arange(bucket) < real_len
                row = jnp.zeros((V,), bool).at[suffix_ids[0]].max(valid)
                last = _apply_repetition_penalty(last, row[None, :], penalty)
            token, rng = _sample(last, config, rng, temperature)
            if use_pen:
                row = row.at[token[0]].set(True)
                presence = jax.lax.dynamic_update_slice(
                    presence, row[None, :], (jnp.asarray(slot, jnp.int32), jnp.int32(0))
                )
            return token[0], pool_cache, presence, rng

        donate = (1, 2) if use_pen else (1,)
        fn = jax.jit(insert, donate_argnums=donate)
        self._insert_fns[bucket] = fn
        return fn

    def _build_chunk(self):
        """THE decode executable: `chunk_size` scan steps over all slots, per-slot
        operands, packed (slot, token) stream output. Compiled exactly once."""
        S, L, chunk = self.num_slots, self.max_length, self.chunk_size
        step_inner = self._step_raw
        use_pen = self.use_repetition_penalty
        paged = self.paged
        config = self._sample_config
        mesh = self.mesh

        def decode_chunk(params, cache, presence, token, pos, active, rem, eos_ids, temperature, penalty, page_table, rng):
            self.trace_counts["decode_chunk"] += 1

            def body(carry, _):
                cache, presence, token, pos, active, rem, rng = carry
                # The page table is loop-invariant: admission reserves a
                # request's whole worst-case footprint up front, so no page
                # boundary crossed mid-chunk ever needs a fresh page.
                if paged:
                    logits, cache = step_inner(params, cache, token, pos, page_table)
                else:
                    logits, cache = step_inner(params, cache, token, pos)
                if use_pen:
                    logits = _apply_repetition_penalty(logits, presence, penalty[:, None])
                nxt, rng = _sample(logits, config, rng, temperature[:, None])
                nxt = jnp.where(active, nxt, jnp.int32(0))
                if use_pen:
                    presence = presence.at[jnp.arange(S), nxt].max(active)
                emitted = active  # every active slot streams exactly one token
                new_rem = jnp.where(active, rem - 1, rem)
                hit_eos = (eos_ids >= 0) & (nxt == eos_ids)
                new_active = active & ~hit_eos & (new_rem > 0)
                new_pos = jnp.where(active, pos + 1, pos)
                return (cache, presence, nxt, new_pos, new_active, new_rem, rng), (nxt, emitted)

            carry = (cache, presence, token, pos, active, rem, rng)
            carry, (toks, valids) = jax.lax.scan(body, carry, None, length=chunk)
            cache, presence, token, pos, active, rem, rng = carry
            cache = constrain_tp_cache(cache, mesh)
            # Pack the [chunk, S] stream TIME-major so each slot's tokens stay in
            # order, valid entries first: composite sort key = invalid*N + time.
            n = chunk * S
            flat_tok = toks.reshape(n)
            flat_valid = valids.reshape(n)
            flat_slot = jnp.broadcast_to(jnp.arange(S)[None, :], (chunk, S)).reshape(n)
            order = jnp.argsort(jnp.where(flat_valid, 0, n) + jnp.arange(n))
            packed = jnp.stack(
                [
                    jnp.where(flat_valid[order], flat_slot[order], -1),
                    jnp.where(flat_valid[order], flat_tok[order], -1),
                ],
                axis=-1,
            ).astype(jnp.int32)
            return cache, presence, token, pos, active, rem, rng, packed, flat_valid.sum()

        donate = (1, 2) if use_pen else (1,)
        return jax.jit(decode_chunk, donate_argnums=donate)

    def _build_spec_chunk(self):
        """THE decode executable, speculative flavor: each of the `chunk_size`
        scan iterations drafts `draft_tokens` continuations per slot with the
        on-device n-gram drafter, scores the pending token plus every draft in
        ONE (draft_tokens+1)-position verify dispatch
        (`make_causal_programs(..., verify_block=True)` through
        `ops.attention.update_slot_cache`'s multi-token path), and emits the
        longest greedily-confirmed draft prefix plus one bonus token — up to
        draft_tokens+1 tokens per slot for one dispatch's latency, 1..k+1
        always, so it can only match or beat the plain chunk. Accept/reject,
        EOS-in-block truncation, budget capping, and the history update all
        run as traced ops: steady state stays this one executable, zero
        recompiles, zero host reads.

        Rejected draft K/V needs no rollback in either cache mode: the slot's
        position simply doesn't advance past the accepted prefix, the
        per-query `cols <= pos` mask keeps stale rows invisible, and the next
        verify block overwrites them before anything can attend them. (Paged:
        rejected writes land through the slot's OWN page table — the draft
        window is part of the admission reservation — or fall through to the
        scratch page past the table's last real entry.)

        An EOS inside the verified block terminates the request THERE: the
        block's tail is discarded (not emitted, not counted against the
        budget), pos stops at the EOS, and the drained result ends with the
        EOS token — exactly the one-token path's `_trim_at_eos` semantics.

        Beyond the plain chunk's outputs it returns two [chunk, S] int32
        matrices: tokens emitted per (iteration, slot) and valid drafts
        proposed — the host folds them into the spec counters/histogram."""
        S, chunk = self.num_slots, self.chunk_size
        H = self.max_length
        verify_inner = self._verify_raw
        paged = self.paged
        k_draft, m_gram = self.draft_tokens, self.draft_ngram
        mesh = self.mesh

        def decode_chunk(params, cache, presence, token, pos, active, rem, eos_ids, temperature, penalty, page_table, rng, history):
            self.trace_counts["decode_chunk"] += 1
            js = jnp.arange(k_draft + 1, dtype=jnp.int32)
            rows = jnp.arange(S)

            def body(carry, _):
                cache, token, pos, active, rem, history = carry
                hist_len = pos + 1  # the pending token sits at history[pos]
                drafts, valid_len = propose_ngram_drafts(history, hist_len, k_draft, m_gram)
                block = jnp.concatenate([token[:, None], drafts], axis=1)  # [S, k+1]
                positions = pos[:, None] + js[None, :]
                if paged:
                    logits, cache = verify_inner(params, cache, block, positions, page_table)
                else:
                    logits, cache = verify_inner(params, cache, block, positions)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k+1]
                accept = greedy_accept_length(drafts, greedy[:, :k_draft], valid_len)
                # Budget cap: emit at most `rem` tokens (accept + 1 bonus).
                accept = jnp.clip(accept, 0, rem - 1)
                emit = active[:, None] & (js[None, :] <= accept[:, None])
                # EOS inside the block ends the request there: keep the EOS,
                # discard the tail.
                eos_hit = emit & (eos_ids[:, None] >= 0) & (greedy == eos_ids[:, None])
                first_eos = jnp.min(jnp.where(eos_hit, js[None, :], k_draft + 1), axis=1)
                emit &= js[None, :] <= first_eos[:, None]
                n_emit = emit.sum(axis=1).astype(jnp.int32)  # [S], 0 for inactive
                new_pos = pos + n_emit
                new_rem = rem - n_emit
                finished_eos = first_eos <= accept
                new_active = active & ~finished_eos & (new_rem > 0)
                last = jnp.take_along_axis(greedy, jnp.clip(n_emit - 1, 0, k_draft)[:, None], axis=1)[:, 0]
                new_token = jnp.where(active, last, token)
                # Append the emitted tokens to the history (the next iteration
                # drafts over them). Emitted index j lands at history[pos+1+j];
                # masked positions write back their own gathered values.
                idx = jnp.clip(pos[:, None] + 1 + js[None, :], 0, H - 1)
                old = jnp.take_along_axis(history, idx, axis=1)
                history = history.at[rows[:, None], idx].set(jnp.where(emit, greedy, old))
                out_tok = jnp.where(emit, greedy, jnp.int32(-1))
                proposed = jnp.where(active, valid_len, 0).astype(jnp.int32)
                carry = (cache, new_token, new_pos, new_active, new_rem, history)
                return carry, (out_tok, emit, n_emit, proposed)

            carry = (cache, token, pos, active, rem, history)
            carry, (toks, valids, emitted_mat, proposed_mat) = jax.lax.scan(body, carry, None, length=chunk)
            cache, token, pos, active, rem, history = carry
            cache = constrain_tp_cache(cache, mesh)
            # Pack [chunk, S, k+1] -> (slot, token) stream, time-major per slot
            # (row-major flatten keeps (iteration, block-index) order within a
            # slot), valid entries first — same composite key as the plain chunk.
            n = chunk * S * (k_draft + 1)
            flat_tok = toks.reshape(n)
            flat_valid = valids.reshape(n)
            flat_slot = jnp.broadcast_to(rows[None, :, None], (chunk, S, k_draft + 1)).reshape(n)
            order = jnp.argsort(jnp.where(flat_valid, 0, n) + jnp.arange(n))
            packed = jnp.stack(
                [
                    jnp.where(flat_valid[order], flat_slot[order], -1),
                    jnp.where(flat_valid[order], flat_tok[order], -1),
                ],
                axis=-1,
            ).astype(jnp.int32)
            return (
                cache, presence, token, pos, active, rem, rng, packed, flat_valid.sum(),
                emitted_mat, proposed_mat,
            )

        return jax.jit(decode_chunk, donate_argnums=(1,))

    # ---------------------------------------------------------------- host plane

    @property
    def pending(self) -> bool:
        """Anything queued or in flight."""
        return bool(self._queue) or bool(self._active.any()) or any(
            r is not None for r in self._slot_request
        )

    @property
    def free_slots(self) -> int:
        return sum(r is None for r in self._slot_request)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the routing/backpressure signal)."""
        return len(self._queue)

    @property
    def slots_in_use(self) -> int:
        return sum(r is not None for r in self._slot_request)

    @property
    def load(self) -> int:
        """Queued + in-flight request count — what least-loaded routing
        compares across replicas (`router.Router`)."""
        return len(self._queue) + sum(r is not None for r in self._slot_request)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def kv_pool_itemsize(self) -> int:
        """Stored bytes per cached K/V VALUE in the live cache (pool leaf
        itemsize) — the honest dtype figure for HBM-traffic estimates, which
        used to be (wrongly, under quantization) read off the params dtype."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(self._cache)[0]:
            name = str(getattr(path[-1], "key", getattr(path[-1], "name", path[-1])))
            if name == "cached_key":
                return int(np.dtype(leaf.dtype).itemsize)
        return int(np.dtype(np.float32).itemsize)

    @property
    def kv_cache_nbytes(self) -> int:
        """Actual stored bytes of the whole slot cache (pools + scale pools
        for quantized dtypes) — the capacity half of the quantization story."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self._cache):
            total += int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
        return total

    @property
    def _home_device(self):
        """The per-chip accounting device: the submesh's first device for a
        mesh-spanning engine, the default device otherwise."""
        if self.mesh is not None:
            return self.mesh.devices.flat[0]
        return jax.devices()[0]

    @property
    def per_device_weight_nbytes(self) -> int:
        """Weight bytes resident on ONE chip, read off the LIVE shardings —
        for a tp=N engine the Megatron-sharded kernels contribute ~1/N each,
        replicated leaves (norms, biases) their full size."""
        return tree_device_nbytes(self._params, self._home_device)

    @property
    def per_device_kv_cache_nbytes(self) -> int:
        """Slot-cache bytes resident on ONE chip (pools sharded by KV head
        contribute ~1/N under tp=N; scalars and pad masks replicate)."""
        return tree_device_nbytes(self._cache, self._home_device)

    def tp_sharding_report(self) -> Dict[str, Dict[str, str]]:
        """{'params': {path: spec}, 'cache': {path: spec}} from the LIVE
        arrays — the audit surface the tp tests and the serving bench read to
        prove nothing fell back to silent full replication (TPU118's runtime
        complement). Single-device engines report every leaf as
        'single-device'."""
        from .parallel.sharding import tree_paths_and_leaves

        def describe(tree):
            out = {}
            for path, leaf in tree_paths_and_leaves(tree)[0]:
                sharding = getattr(leaf, "sharding", None)
                spec = getattr(sharding, "spec", None)
                out[path] = str(spec) if spec is not None else "single-device"
            return out

        return {"params": describe(self._params), "cache": describe(self._cache)}

    @property
    def stats(self) -> Dict[str, Any]:
        """Back-compat health view, computed from the metrics registry (the
        source of truth since the telemetry PR). Same keys and meanings as the
        old ad-hoc dict; mutate nothing here — it is rebuilt per access."""
        view: Dict[str, Any] = {
            "attention_impl": self.attention_impl,
            "weight_dtype": self.weight_dtype,
            "kv_cache_dtype": self.kv_cache_dtype,
            "tp": self.tp,
            "inserts": int(self._m_inserts.value),
            "chunks": int(self._m_chunks.value),
            "decode_steps": int(self._m_decode_steps.value),
            "queue_peak": int(self._m_queue_peak.value),
            "finish_reasons": {
                reason: int(counter.value) for reason, counter in self._m_finish.items()
            },
        }
        if self.speculative:
            steps = int(self._m_spec_steps.value)
            accepted = int(self._m_spec_accepted.value)
            view["speculative"] = {
                "draft_tokens": self.draft_tokens,
                "draft_ngram": self.draft_ngram,
                "verify_steps": steps,
                "drafted": int(self._m_spec_drafted.value),
                "accepted": accepted,
                "rejected": int(self._m_spec_rejected.value),
                # The headline: mean tokens emitted per verify step. 1.0 means
                # speculation never helped; k+1 is the ceiling.
                "accepted_tokens_per_step": round((steps + accepted) / steps, 4) if steps else None,
            }
        if self.paged:
            view["pages_total"] = self.pool.pages_total
            view["pages_in_use"] = self.pool.pages_in_use
            view["prefix_cache"] = {
                "enabled": self.use_prefix_cache,
                "hits": int(self._m_prefix_hits.value),
                "misses": int(self._m_prefix_misses.value),
                "evictions": int(self._m_prefix_evictions.value),
                "prefill_tokens_saved": int(self._m_prefill_saved.value),
                "entries": self.pool.prefix_entries,
                "cached_pages": self.pool.pages_cached,
            }
        return view

    def _update_occupancy_gauges(self):
        """Refresh the point-in-time gauges (queue depth, slot occupancy) —
        called wherever the queue or the slot map changes."""
        depth = len(self._queue)
        self._m_queue_depth.set(depth)
        self._m_queue_peak.set_max(depth)
        in_use = sum(r is not None for r in self._slot_request)
        self._m_slots_in_use.set(in_use)
        self._m_slot_utilization.set(in_use / self.num_slots)
        if self.paged:
            self._m_pages_in_use.set(self.pool.pages_in_use)

    def submit(self, request: Request) -> int:
        """Validate + enqueue. Raises `ValueError` for malformed requests (the
        caller's bug, reported synchronously), `QueueFull` for backpressure, and
        `EngineClosed` after `close()`/during `drain()` — none of which disturb
        requests already in flight."""
        if self._closed:
            raise EngineClosed("engine is closed")
        if self._draining:
            raise EngineClosed("engine is draining; resubmit after drain() returns")
        ids = np.asarray(request.input_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if ids.size + request.max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({request.max_new_tokens}) "
                f"exceeds the {self.max_length}-token slot capacity"
            )
        if self.paged:
            need = self._pages_needed(int(ids.size), request.max_new_tokens)
            if need > self.pool.pages_total:
                raise ValueError(
                    f"request needs {need} KV pages ({ids.size} prompt + "
                    f"{request.max_new_tokens} new tokens"
                    + (f" + {self.draft_tokens} draft-window" if self.speculative else "")
                    + f" at page_size {self.page_size}) but the pool holds "
                    f"{self.pool.pages_total}"
                )
        if request.request_id in self.results:
            raise ValueError(f"duplicate request_id {request.request_id}")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"wait queue is at max_queue={self.max_queue}; shed load or retry later"
            )
        self.results[request.request_id] = RequestResult(
            request.request_id, arrival_time=request.arrival_time
        )
        if request.deadline_s is not None:
            self._deadlines[request.request_id] = time.perf_counter() + float(request.deadline_s)
        self._submit_times[request.request_id] = time.perf_counter()
        self._queue.append(dataclasses.replace(request, input_ids=ids))
        self._m_submitted.inc()
        span = self.tracer.start_span(
            "serve.request", category="serve",
            request_id=int(request.request_id), prompt_tokens=int(ids.size),
            max_new_tokens=int(request.max_new_tokens),
        )
        span.event("submitted", queue_depth=len(self._queue))
        self._request_spans[request.request_id] = span
        self._update_occupancy_gauges()
        return request.request_id

    def _pages_needed(self, prompt_tokens: int, max_new: int) -> int:
        """A request's page reservation: its worst-case token footprint, plus —
        speculative engines — the draft window, whose rejected verify writes
        land through the slot's own page table (capped at the table width; the
        cache clips overshoot to its never-attended last cell)."""
        window = self.draft_tokens if self.speculative else 0
        return min(pages_for(prompt_tokens + max_new + window, self.page_size), self.pages_per_slot)

    # ------------------------------------------------------------- fault isolation
    def _cache_consumed(self) -> bool:
        """True when a failed dispatch actually CONSUMED the donated slot cache
        (its buffers are deleted) — accelerators only; CPU ignores donation.
        Donation is all-or-nothing per dispatch, so the first leaf decides."""
        for leaf in jax.tree_util.tree_leaves(self._cache):
            is_deleted = getattr(leaf, "is_deleted", None)
            return bool(is_deleted()) if callable(is_deleted) else False
        return False

    def _abort_in_flight(self, exc: Exception, now: Optional[float] = None):
        """The shared-state blast radius: a dispatch failure that took the slot
        cache with it (the decode chunk always; an insert only when its donated
        operands were consumed). Every in-flight request errors (partial tokens
        kept) and the cache is rebuilt from zeros — the donated buffers may
        already be invalidated, and keeping the references would poison every
        later insert with a deleted-buffer error, leaving the engine up but
        failing every future request. New admissions overwrite their own rows
        before they are ever attended, exactly as at engine construction."""
        now = time.perf_counter() if now is None else now
        self.tracer.event(
            "serve.blast_radius", category="serve",
            errored_requests=sum(r is not None for r in self._slot_request),
            error=repr(exc),
        )
        for slot, result in enumerate(self._slot_request):
            if result is not None:
                self._finish(result, "error", now=now, slot=slot, error=repr(exc))
        self._active[:] = False
        self._cache = self._init_cache()
        if self._presence is not None:
            self._presence = jnp.zeros((self.num_slots, self.base_config.vocab_size), bool)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                self._presence = jax.device_put(
                    self._presence, NamedSharding(self.mesh, PartitionSpec())
                )
        if self.speculative:
            # The speculative state dies with the cache: every slot's drafting
            # context belonged to a request that just errored. Admissions
            # reseed their own rows.
            self._history[:] = 0
        if self.paged:
            # The pool CONTENT died with the donated buffers: every refcount,
            # page-table row and — critically — prefix registration goes with
            # it (a stale hash->page mapping would serve zeroed KV as a
            # "cached" prefix to the next shared-prompt request).
            self.pool.reset()
            self._page_table[:] = SCRATCH_PAGE
            self._slot_pages = [[] for _ in range(self.num_slots)]
            self._m_pages_in_use.set(0)

    def _slot_of(self, request_id: int) -> Optional[int]:
        for slot, result in enumerate(self._slot_request):
            if result is not None and result.request_id == request_id:
                return slot
        return None

    def _finish(self, result: RequestResult, reason: str, now: Optional[float] = None,
                slot: Optional[int] = None, error: Optional[str] = None):
        """The single exit path for a request: stamp the result, bump the
        per-reason counter, drop its deadline, and free its slot (if any) so the
        next `_admit` can reuse the cache rows."""
        result.finished = True
        result.finish_time = time.perf_counter() if now is None else now
        result.finish_reason = reason
        if error is not None:
            result.error = error
        span = self._request_spans.pop(result.request_id, None)
        if span is not None:
            span.annotate(finish_reason=reason, tokens=len(result.tokens))
            if error is not None:
                span.annotate(error=error)
            span.end()
        self._m_finish[reason].inc()
        self._deadlines.pop(result.request_id, None)
        self._submit_times.pop(result.request_id, None)
        if slot is not None:
            self._slot_request[slot] = None
            self._active[slot] = False
            if self.paged:
                # Release the slot's page references (a shared prefix page
                # drops to CACHED at refcount 0, private pages go free) and
                # point the table row at the scratch page so any residual
                # write for this row is discarded.
                if self._slot_pages[slot]:
                    self.pool.release(self._slot_pages[slot])
                    self._slot_pages[slot] = []
                self._page_table[slot] = SCRATCH_PAGE
        self._update_occupancy_gauges()

    def _drop_queued(self, request_id: int) -> bool:
        before = len(self._queue)
        self._queue = deque(r for r in self._queue if r.request_id != request_id)
        return len(self._queue) != before

    def _expire_deadlines(self):
        """Step-boundary deadline sweep: queued requests time out without ever
        occupying a slot; in-flight ones keep their partial tokens and free the slot."""
        if not self._deadlines:
            return
        now = time.perf_counter()
        for request_id in [rid for rid, t in self._deadlines.items() if now >= t]:
            result = self.results[request_id]
            if result.finished:
                self._deadlines.pop(request_id, None)
                continue
            self._drop_queued(request_id)
            self._finish(result, "timeout", now=now, slot=self._slot_of(request_id))

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request: its result finishes with
        `finish_reason="cancelled"` (partial tokens kept) and its slot frees for
        the next admission. Returns False if it already finished; raises
        KeyError for an unknown id."""
        result = self.results[request_id]
        if result.finished:
            return False
        self._drop_queued(request_id)
        self._finish(result, "cancelled", slot=self._slot_of(request_id))
        return True

    def _admit(self) -> List[Tuple[int, List[int]]]:
        """Fill free slots from the queue (FIFO). Each admission is one insert
        dispatch; the first token streams out immediately (TTFT).

        Paged admission is PAGE-based, not slot-based: the request reserves
        `ceil((prompt + max_new) / page_size)` pool pages minus whatever its
        prompt prefix already shares from the prefix cache — so a mix of small
        requests can occupy every slot even when the pool is far smaller than
        `num_slots * max_length` worst-case rows. When the pool (plus evictable
        cached prefix pages) cannot cover the next request, it returns to the
        FRONT of the queue and admission pauses until in-flight requests
        release pages — FIFO order and guaranteed progress, since reserve-on-
        admit means every admitted request runs to completion.

        Error isolation: an exception from ONE request's insert (transient device
        error, a prompt the compiled program rejects) finishes only that request
        with `finish_reason="error"` — the queue keeps draining and every other
        slot keeps serving."""
        events: List[Tuple[int, List[int]]] = []
        while self._queue and self.free_slots:
            req = self._queue.popleft()
            slot = self._slot_request.index(None)
            ids = req.input_ids
            p = int(ids.size)
            result = self.results[req.request_id]
            pages: List[int] = []
            hashes: List[str] = []
            matched_pages = 0
            matched_len = 0
            if self.paged:
                total_pages = self._pages_needed(p, req.max_new_tokens)
                if self.use_prefix_cache:
                    hashes = chain_hashes(ids, self.page_size)
                    # Cap below the whole prompt: the last real token always
                    # reruns so the insert has first-token logits to sample.
                    shared = self.pool.match_prefix(hashes, min(len(hashes), (p - 1) // self.page_size))
                else:
                    shared = []
                matched_pages = len(shared)
                # Closed-bucket planning: when the pow2 suffix bucket would
                # overflow the cache window (`matched_len + bucket >
                # _padded_length`), DROP trailing matched pages instead of
                # minting a matched_len-dependent capped bucket — an open set
                # of bucket sizes no warmup can enumerate, and the source of
                # the first-hit insert recompiles the bench's 0-recompile
                # assert used to trip at non-default --max-new-max sizes.
                _bucket, keep_pages = self.plan_admission_bucket(
                    p, matched_pages, self.page_size, self._padded_length
                )
                while matched_pages > keep_pages:
                    self.pool.release([shared.pop()])
                    matched_pages -= 1
                matched_len = matched_pages * self.page_size
                private = self.pool.reserve(total_pages - matched_pages)
                if private is None:
                    if shared:
                        self.pool.release(shared)
                    self._queue.appendleft(req)
                    break
                pages = shared + private
                if self.use_prefix_cache:
                    full_pages = p // self.page_size
                    self._m_prefix_hits.inc(matched_pages)
                    self._m_prefix_misses.inc(max(full_pages - matched_pages, 0))
                    if matched_len:
                        self._m_prefill_saved.inc(matched_len)
                suffix = p - matched_len
                bucket = _bucket
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :suffix] = ids[matched_len:]
                page_row = np.zeros((self.pages_per_slot,), np.int32)
                page_row[: len(pages)] = pages
            else:
                bucket = min(_bucket_for(p), self.max_length)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :p] = ids
            rspan = self._request_spans.get(req.request_id)
            admit_t0 = time.perf_counter()
            if rspan is not None:
                submitted_at = self._submit_times.get(req.request_id)
                rspan.event(
                    "admitted", slot=slot, bucket=int(bucket),
                    queue_wait_s=round(admit_t0 - submitted_at, 6) if submitted_at is not None else None,
                    prefix_hit_pages=int(matched_pages), pages_reserved=len(pages),
                )
            ispan = self.tracer.start_span(
                "serve.insert", category="serve", parent=rspan,
                request_id=int(req.request_id), slot=slot, bucket=int(bucket),
                suffix_tokens=int(p - matched_len), prefix_hit_pages=int(matched_pages),
            )
            try:
                fn = self._insert_fn(bucket)
                if self.paged:
                    token, self._cache, self._presence, self._rng = fn(
                        self.params,
                        self._cache,
                        self._presence,
                        jnp.asarray(padded),
                        _operand(p - matched_len, np.int32),
                        _operand(matched_len, np.int32),
                        _operand(matched_pages, np.int32),
                        jnp.asarray(page_row),
                        _operand(slot, np.int32),
                        _operand(req.temperature, np.float32),
                        _operand(req.repetition_penalty, np.float32),
                        self._rng,
                    )
                else:
                    token, self._cache, self._presence, self._rng = fn(
                        self.params,
                        self._cache,
                        self._presence,
                        jnp.asarray(padded),
                        _operand(p, np.int32),
                        _operand(slot, np.int32),
                        _operand(req.temperature, np.float32),
                        _operand(req.repetition_penalty, np.float32),
                        self._rng,
                    )
                token = int(token)
                ispan.end()
            except Exception as exc:  # noqa: BLE001 — isolate, report, keep serving
                ispan.annotate(error=repr(exc)).end()
                if pages:
                    self.pool.release(pages)
                if self.trace_guard is not None:
                    self.trace_guard.observe(exc)
                logger.warning(
                    "insert failed for request %s (isolated): %r", req.request_id, exc
                )
                self._finish(result, "error", error=repr(exc))
                # Per-request isolation holds only while the shared cache is
                # intact. The insert fn donates (cache, presence) too: if this
                # failed dispatch consumed them (chaos-surfaced hazard — the
                # same poisoning the chunk path guards against), the state is
                # gone for EVERY slot — widen to the blast-radius recovery.
                if self._cache_consumed():
                    logger.warning(
                        "failed insert consumed the donated slot cache; erroring "
                        "%d in-flight request(s) and rebuilding",
                        sum(r is not None for r in self._slot_request),
                    )
                    self._abort_in_flight(exc)
                continue
            if self.paged and self.use_prefix_cache:
                # The insert just wrote this prompt's full pages: register them
                # so the NEXT request with the same prefix shares instead of
                # prefilling. Decode writes land at pos >= prompt_len, past
                # every full prompt page, so registered content stays frozen.
                self.pool.register_prefix(hashes[: p // self.page_size], pages, start=matched_pages)
            now = time.perf_counter()
            self._m_inserts.inc()
            submitted_at = self._submit_times.get(req.request_id)
            if submitted_at is not None:
                self._m_ttft.observe(now - submitted_at)
            if rspan is not None:
                rspan.event("first_token")
            self._slot_last_event[slot] = now
            result.tokens.append(token)
            result.first_token_time = now
            events.append((req.request_id, [token]))

            eos = -1 if req.eos_token_id is None else int(req.eos_token_id)
            rem = req.max_new_tokens - 1
            active = rem > 0 and token != eos
            if active:
                self._slot_request[slot] = result
                self._token[slot] = token
                self._pos[slot] = p  # the first generated token's write position
                self._active[slot] = True
                self._rem[slot] = rem
                self._eos[slot] = eos
                self._temp[slot] = req.temperature
                self._pen[slot] = req.repetition_penalty
                if self.speculative:
                    # Seed the drafter's context: full prompt (prefix-cache
                    # hits included — the host has the whole prompt even when
                    # the insert only saw the suffix) plus the first token.
                    self._history[slot, :p] = ids
                    self._history[slot, p] = token
                    self._history[slot, p + 1:] = 0
                if self.paged:
                    self._slot_pages[slot] = pages
                    self._page_table[slot] = page_row
            else:
                if pages:
                    # One-token request: its pages release immediately — but a
                    # prefix it just registered stays CACHED for the next hit.
                    self.pool.release(pages)
                self._finish(result, "eos" if token == eos else "length", now=now)
        self._update_occupancy_gauges()
        return events

    def release(self, request_id: int) -> RequestResult:
        """Drop a FINISHED request's result and free its id for reuse. `results`
        is never evicted on its own — a long-running server must release each
        request once its consumer has drained it, or host memory grows linearly
        in total requests served."""
        result = self.results[request_id]
        if not result.finished:
            raise ValueError(f"request {request_id} is still in flight")
        del self.results[request_id]
        return result

    def step(self) -> List[Tuple[int, List[int]]]:
        """One serving cycle: expire deadlines → admit → one decode-chunk
        dispatch → drain the packed stream. Returns `(request_id, new_tokens)`
        events in stream order (admissions' first tokens included)."""
        if self._closed:
            return []
        self._expire_deadlines()
        events = self._admit()
        if not self._active.any():
            return events
        chunk_t0 = time.perf_counter()
        # One batched span per chunk dispatch: every active request rides it,
        # so the slot annotation (not N per-request spans) is what keeps the
        # flight recorder's ring proportional to dispatches, not tokens.
        chunk_span = self.tracer.start_span(
            "serve.decode_chunk", category="serve",
            chunk_size=self.chunk_size,
            active_slots=int(self._active.sum()),
            slots=",".join(str(i) for i in np.nonzero(self._active)[0]),
            pages_in_use=self.pool.pages_in_use if self.paged else None,
        )
        pos_before = self._pos.copy()  # spec: where each slot's drained tokens append
        try:
            args = [
                self.params,
                self._cache,
                self._presence,
                jnp.asarray(self._token),
                jnp.asarray(self._pos),
                jnp.asarray(self._active),
                jnp.asarray(self._rem),
                jnp.asarray(self._eos),
                jnp.asarray(self._temp),
                jnp.asarray(self._pen),
                jnp.asarray(self._page_table),
                self._rng,
            ]
            if self.speculative:
                args.append(jnp.asarray(self._history))
            out = self._chunk_fn(*args)
            # np.array (copy): np.asarray of a jax buffer is a READ-ONLY view,
            # and these mirrors are written in-place at the next admission.
            # The readback sits INSIDE the try: on accelerators the dispatch
            # is async, so a device-side failure surfaces here rather than at
            # the enqueue above — it is the same blast radius.
            new_cache, new_presence = out[0], out[1]
            token, pos, active, rem = (np.array(x) for x in out[2:6])
            packed, count = np.asarray(out[7]), int(out[8])
            spec_emitted = np.asarray(out[9]) if self.speculative else None
            spec_proposed = np.asarray(out[10]) if self.speculative else None
        except Exception as exc:  # noqa: BLE001
            if self.trace_guard is not None:
                self.trace_guard.observe(exc)
            # The ONE shared executable covers every slot: if the dispatch itself
            # dies the in-flight cache state is unrecoverable, so every in-flight
            # request errors (partial tokens kept) — but the engine itself stays
            # up: slots free, the queue keeps draining, new admissions rebuild
            # their own cache rows from scratch.
            in_flight = sum(r is not None for r in self._slot_request)
            logger.warning("decode chunk dispatch failed; erroring %d in-flight request(s): %r",
                           in_flight, exc)
            chunk_span.annotate(error=repr(exc)).end()
            self._abort_in_flight(exc)
            return events
        self._cache, self._presence = new_cache, new_presence
        self._rng = out[6]
        self._m_chunks.inc()
        self._m_decode_steps.inc(self.chunk_size)
        if self.speculative:
            # Fold the chunk's per-(iteration, slot) emit/propose matrices into
            # the spec ledger. Every count is a host scalar off the readback.
            steps = int((spec_emitted > 0).sum())
            emitted_total = int(spec_emitted.sum())
            proposed_total = int(spec_proposed.sum())
            accepted = emitted_total - steps  # each step emits accepted + 1
            self._m_spec_steps.inc(steps)
            self._m_spec_drafted.inc(proposed_total)
            self._m_spec_accepted.inc(accepted)
            self._m_spec_rejected.inc(proposed_total - accepted)
            for v in spec_emitted[spec_emitted > 0]:
                self._m_spec_hist.observe(float(v))
            chunk_span.annotate(
                spec_verify_steps=steps,
                spec_tokens_emitted=emitted_total,
                spec_drafts_accepted=accepted,
                spec_drafts_proposed=proposed_total,
            )

        per_slot: Dict[int, List[int]] = {}
        for slot, tok in packed[:count]:
            per_slot.setdefault(int(slot), []).append(int(tok))
        now = time.perf_counter()
        # The chunk's wall clock (dispatch + packed-stream drain) — measured
        # AFTER the np.asarray readback above, so it covers real device work,
        # not just the async enqueue.
        self._m_chunk_latency.observe(max(now - chunk_t0, 0.0))
        chunk_span.annotate(tokens_streamed=count).end()
        self.tracer.recorder.poll()  # serve the `trace dump` touch file
        for slot, toks in per_slot.items():
            result = self._slot_request[slot]
            if result is None:  # defensive: stream for a freed slot
                continue
            result.tokens.extend(toks)
            if self.speculative:
                # Mirror the device-side history update (emitted token j of the
                # chunk landed at history[pos_before + 1 + j]) so the next
                # dispatch pushes an identical context.
                start = int(pos_before[slot]) + 1
                self._history[slot, start : start + len(toks)] = toks
            events.append((result.request_id, toks))
            # Inter-token latency: the host drains a slot's tokens once per
            # chunk, so the per-token gap is the drain gap amortized over the
            # tokens it delivered (one observation per token keeps histogram
            # weights proportional to tokens served).
            last = self._slot_last_event[slot]
            if last > 0.0 and toks:
                gap = max(now - last, 0.0) / len(toks)
                for _ in toks:
                    self._m_inter_token.observe(gap)
            self._slot_last_event[slot] = now

        was_active = self._active
        self._token, self._pos, self._rem = token, pos, rem
        self._active = active
        for slot in np.nonzero(was_active & ~active)[0]:
            result = self._slot_request[slot]
            if result is not None:
                reason = (
                    "eos" if result.tokens and result.tokens[-1] == self._eos[slot] else "length"
                )
                self._finish(result, reason, now=now, slot=slot)
        return events

    def run(self, requests: Optional[List[Request]] = None) -> Dict[int, np.ndarray]:
        """Drive to completion: submit `requests` (if given), loop `step()` until
        the queue and every slot drain, return {request_id: generated tokens}."""
        for req in requests or ():
            self.submit(req)
        while self.pending:
            self.step()
        return {rid: np.asarray(r.tokens, np.int32) for rid, r in self.results.items()}

    # ------------------------------------------------------------------ lifecycle
    def drain(self) -> Dict[int, RequestResult]:
        """Flush: refuse new submissions while finishing everything queued and
        in flight, then reopen. Returns the full results map (the caller
        `release()`s what it has consumed)."""
        self._draining = True
        try:
            while self.pending:
                self.step()
        finally:
            self._draining = False
        return self.results

    def close(self) -> Dict[int, RequestResult]:
        """Terminal shutdown: everything still queued or in flight finishes with
        `finish_reason="cancelled"` (partial tokens kept), and the engine
        permanently refuses new work (`submit` raises `EngineClosed`, `step`
        no-ops). Idempotent."""
        if self._closed:
            return self.results
        now = time.perf_counter()
        self._queue.clear()
        for slot, result in enumerate(self._slot_request):
            if result is not None:
                self._finish(result, "cancelled", now=now, slot=slot)
        for result in self.results.values():
            if not result.finished:  # still queued (never admitted)
                self._finish(result, "cancelled", now=now)
        self._active[:] = False
        self._closed = True
        self._update_occupancy_gauges()
        return self.results

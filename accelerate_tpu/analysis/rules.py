"""The TPU-hazard rule registry.

Each rule names one mechanical way the repo's compile-once discipline breaks:
a host sync on a traced value, a recompile trigger, a donation misuse, or an
in-repo convention violation. Rules are data (`Rule`), detection lives in
`linter.py` — the registry is what the CLI catalog, the docs table, and the
suppression parser all key on.

Severity ladder:
  - ``error``  — breaks the discipline outright (host sync inside a jitted
    program, donated buffer reused): CI fails on these (`--fail-on error`).
  - ``warn``   — a recompile / throughput hazard that has legitimate uses
    (module-level jit in a script, a per-step ``float(loss)`` for logging);
    reviewers decide, ``--fail-on warn`` opts a tree into strictness.
  - ``info``   — style-level observations.
"""

from __future__ import annotations

from dataclasses import dataclass

#: Ordered severities, weakest first. Comparisons use list position.
SEVERITIES = ("info", "warn", "error")


@dataclass(frozen=True)
class Rule:
    """One linter rule: a stable id (``TPU1xx``), a short slug used in
    suppression comments (`# tpu-lint: disable=<id or slug>`), the severity it
    reports at, and a fixit hint rendered with every finding."""

    id: str
    slug: str
    severity: str
    summary: str
    fixit: str

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r} for rule {self.id}")


RULES = (
    Rule(
        id="TPU101",
        slug="host-sync-item",
        severity="error",
        summary=".item() on a traced value inside jit-reachable code",
        fixit="keep the value on device (jnp ops) or return it from the jitted "
        "program and read it at the step boundary",
    ),
    Rule(
        id="TPU102",
        slug="host-scalar-cast",
        severity="error",
        summary="float()/int()/bool() on a traced array inside jit-reachable code",
        fixit="use jnp.float32(x)/x.astype(...) to stay traced; host casts force "
        "a device sync and fail under jit",
    ),
    Rule(
        id="TPU103",
        slug="host-transfer-numpy",
        severity="error",
        summary="np.asarray/np.array/jax.device_get on a traced value inside "
        "jit-reachable code",
        fixit="use jnp equivalents inside the program; device_get/np conversion "
        "belongs at the step boundary",
    ),
    Rule(
        id="TPU104",
        slug="traced-bool-branch",
        severity="error",
        summary="Python if/while on a traced array (implicit bool()) inside "
        "jit-reachable code",
        fixit="branch with jnp.where / jax.lax.cond / jax.lax.select; Python "
        "control flow on traced values raises TracerBoolConversionError",
    ),
    Rule(
        id="TPU105",
        slug="closure-scalar-capture",
        severity="warn",
        summary="Python scalar from an enclosing scope captured by a jitted "
        "closure (baked in at trace time)",
        fixit="pass the scalar as an operand (jnp.float32(x) argument) so "
        "changing it never recompiles; closure captures are compile-time "
        "constants",
    ),
    Rule(
        id="TPU106",
        slug="jit-in-loop",
        severity="warn",
        summary="jax.jit(...) called inside a loop body (fresh cache per "
        "iteration)",
        fixit="hoist the jax.jit call out of the loop (or memoize per static "
        "key) so the executable cache survives iterations",
    ),
    Rule(
        id="TPU107",
        slug="static-argnums-varying",
        severity="error",
        summary="a static_argnums position fed a loop-varying value (recompile "
        "every iteration)",
        fixit="pass per-step values as traced operands; reserve static_argnums "
        "for genuinely constant configuration",
    ),
    Rule(
        id="TPU108",
        slug="donated-reuse",
        severity="error",
        summary="an argument donated via donate_argnums is read again after "
        "the call",
        fixit="rebind the name to the call's output (the donated buffer is "
        "invalidated in place) or drop the donation",
    ),
    Rule(
        id="TPU109",
        slug="module-level-jit",
        severity="warn",
        summary="jax.jit invoked at module import time",
        fixit="build jitted callables lazily (inside a function/class) so "
        "importing the module never traces or touches a backend",
    ),
    Rule(
        id="TPU110",
        slug="pjit-no-sharding",
        severity="warn",
        summary="pjit without in_shardings/out_shardings annotations",
        fixit="annotate shardings explicitly (or use jax.jit + "
        "with_sharding_constraint); unannotated pjit silently replicates",
    ),
    Rule(
        id="TPU111",
        slug="loop-host-sync",
        severity="warn",
        summary="per-iteration host sync (float()/.item()) on a stepped value "
        "inside a host loop",
        fixit="accumulate on device and read once at the epoch/loop boundary; "
        "a per-step sync serializes dispatch against the device",
    ),
    Rule(
        id="TPU112",
        slug="span-host-sync",
        severity="warn",
        summary="device-value read (.item()/float()/np.asarray) used in a "
        "tracer span/event annotation or inside a `with tracer.span(...)` block",
        fixit="read device values at the step boundary (np.asarray/.item() on "
        "already-fetched outputs) and annotate spans with host scalars; an "
        "instrumentation-side read hides a blocking device sync in the very "
        "code that exists to observe the hot path",
    ),
    Rule(
        id="TPU113",
        slug="blocking-ckpt-in-jit",
        severity="error",
        summary="blocking checkpoint I/O (save_pytree/atomic_write/save_state/"
        "file_sha256/...) called inside jit-reachable code",
        fixit="checkpoint at the step boundary from host code — snapshot the "
        "state (snapshot_pytree) and hand it to save_state (async_save=True "
        "commits it on the background committer); serialize+fsync inside a "
        "traced program is a host sync at best and a trace error at worst",
    ),
    Rule(
        id="TPU114",
        slug="unbounded-serving-queue",
        severity="warn",
        summary="ContinuousBatcher/Router constructed without bounded queue "
        "backpressure (max_queue) — or a Router without a default request "
        "deadline — in jit-adjacent serving code",
        fixit="pass max_queue=<bound> so overload surfaces as QueueFull "
        "backpressure instead of unbounded host-memory growth, and give "
        "Router a default_deadline_s=<seconds> so every request reaches a "
        "terminal finish_reason even when a replica stalls",
    ),
    Rule(
        id="TPU115",
        slug="kernel-fallback",
        severity="warn",
        summary='serving decode/verify programs pinned to attention_impl="xla" '
        "where the Pallas paged kernel applies, or a Pallas attention kernel "
        "forced into interpret mode outside test code",
        fixit='pass attention_impl="pallas_paged" for paged serving engines (the '
        "XLA gather path materializes the whole logical cache per decode "
        "dispatch and exists as the parity oracle, not the hot path) — or "
        "suppress where the oracle is deliberate; interpret=True is the "
        "CPU-test shim, production call sites must let the kernel compile "
        "(interpret=None auto-selects)",
    ),
    Rule(
        id="TPU116",
        slug="worker-loop-no-heartbeat",
        severity="warn",
        summary="subprocess worker loop without a heartbeat deadline, or an IPC "
        "recv with no timeout inside a loop",
        fixit="pass heartbeat_deadline_s=<seconds> to serve_worker/WorkerLoop (an "
        "orphaned worker must exit, not leak a process + device memory) and give "
        "every looped recv_frame/recv_message a timeout_s=<seconds> — an unbounded "
        "IPC read turns a hung peer into a hung fleet controller, invisible to the "
        "health machine that exists to catch it",
    ),
    Rule(
        id="TPU117",
        slug="quant-scale-literal",
        severity="warn",
        summary="a quantization scale passed as a Python numeric literal to a "
        "serving attention/kernel seam, or a kv_cache_dtype literal off the "
        "supported set",
        fixit="thread scales as traced ARRAY operands (the pool's parallel "
        "key_scale/value_scale arrays) — a Python scalar bakes the scale into "
        "the executable at trace time, so every scale change retraces the "
        'decode program; kv_cache_dtype must be one of "bf16" | "int8" | '
        '"fp8_e4m3" (static config, ops/quantization.KV_CACHE_DTYPES) — an '
        "off-set literal fails at engine construction, or worse, silently "
        "selects nothing",
    ),
    Rule(
        id="TPU118",
        slug="tp-replicated-operand",
        severity="warn",
        summary="a mesh-spanning serving module places params/pool trees with "
        "device_put but no NamedSharding — the tree lands on one device and "
        "jit replicates it to every chip (silent full replication)",
        fixit="pass a NamedSharding pytree to device_put (derive it with "
        "parallel.sharding.derive_tp_param_shardings / "
        "derive_tp_cache_shardings from the model family's Megatron rules) — "
        "or build the engine with ContinuousBatcher(tp=N), whose params "
        "setter and cache init place everything sharded; an unsharded "
        "placement serves token-identically while spending N x the per-chip "
        "HBM the mesh exists to save (the accidental-fallback analogue of "
        "TPU115)",
    ),
    Rule(
        id="TPU119",
        slug="dead-partition-rule",
        severity="warn",
        summary="a (pattern, spec) entry in a sharding-rules table whose regex "
        "matches no parameter path of the model it ships with, or a literal "
        "per-leaf PartitionSpec scattered in model code outside the rule "
        "tables",
        fixit="delete the dead entry (or fix its regex to name a module the "
        "model actually defines) — an entry that matches nothing silently "
        "replicates the weight it was written to shard, the same failure the "
        "planner's audit would catch; and keep per-leaf PartitionSpecs out of "
        "model code: route them through the family's *_SHARDING_RULES table "
        "or let sharding_rules=\"auto\" (parallel.planner) emit the table, so "
        "every placement decision stays visible to the one derivation seam",
    ),
    Rule(
        id="TPU120",
        slug="replicated-optimizer-state",
        severity="warn",
        summary="a module that builds a training mesh with a \"data\" axis "
        "places an optimizer-state tree with device_put but no (or a "
        "replicated) sharding — fp32 Adam moments are 8 bytes/param on EVERY "
        "chip, the single largest avoidable HBM account in data-parallel "
        "training",
        fixit="shard the weight update: derive the state's placement with "
        "parallel.sharding.derive_opt_state_shardings (pass the planner's "
        "opt_rules table for ZeRO sharding along \"data\" even where params "
        "replicate — plan_train_sharding emits it), or prepare the optimizer "
        "through Accelerator.prepare with sharding_rules=\"auto\", whose "
        "AcceleratedOptimizer init/out_shardings discipline places moments "
        "sharded from the first step; reduce-scatter + all-gather moves the "
        "same ICI bytes the all-reduce already paid, so the sharded update "
        "is pure per-chip-HBM savings (Xu et al., cross-replica weight-update "
        "sharding)",
    ),
    Rule(
        id="TPU121",
        slug="host-hop-in-stage-handoff",
        severity="warn",
        summary="a module that builds a \"pipeline\" mesh axis moves an "
        "inter-stage activation/gradient carry through the host — "
        "jax.device_get, a numpy coercion (np.asarray/np.array), or "
        ".block_until_ready() on the handoff path serializes the 1F1B "
        "schedule on PCIe and stalls every stage behind the transfer",
        fixit="ship the carry submesh-to-submesh with jax.device_put(carry, "
        "NamedSharding(next_stage_mesh, spec)) — a pure device-to-device ICI "
        "transfer that async dispatch overlaps with the other stages' compute "
        "(parallel.mpmd's _ship seam); keep TraceGuard armed around the step "
        "so any host round-trip that does sneak in fails loudly instead of "
        "silently flattening the pipeline",
    ),
    Rule(
        id="TPU122",
        slug="unbounded-reconnect",
        severity="warn",
        summary="a serving-transport module reconnects or reads the wire "
        "without a bound — socket.create_connection with no timeout, a "
        "recv loop on a socket that was never given a deadline, or a "
        "reconnect retried in a loop with neither a backoff cap nor a "
        "deadline budget — one partitioned peer then hangs the controller "
        "(or hot-loops the dial) instead of surfacing a transport fault "
        "the fleet can route around",
        fixit="bound every wire wait: dial with "
        "socket.create_connection(addr, timeout=...), arm a deadline before "
        "protocol reads (settimeout, or select-based framing like "
        "worker.recv_frame's timeout_s), and drive reconnect attempts "
        "through a budgeted state machine — capped exponential backoff plus "
        "a reconnect_deadline_s that escalates to the worker-death/respawn "
        "path when exhausted (worker.SubprocessEngine is the reference "
        "shape: reconnect(timeout_s=...) per attempt, never a bare retry "
        "loop)",
    ),
)

RULES_BY_ID = {r.id: r for r in RULES}
RULES_BY_SLUG = {r.slug: r for r in RULES}


def resolve_rule(token: str):
    """A suppression/CLI token -> Rule, accepting either the id or the slug
    (case-insensitive). Returns None for unknown tokens — suppressions never
    crash a lint run."""
    token = token.strip()
    return RULES_BY_ID.get(token.upper()) or RULES_BY_SLUG.get(token.lower())


def severity_at_least(severity: str, floor: str) -> bool:
    return SEVERITIES.index(severity) >= SEVERITIES.index(floor)

"""TPU-hazard analysis: a static linter for the compile-once discipline plus a
runtime trace guard that proves it holds on a live step.

Two halves, one contract:

  - **Static** (`rules`, `linter`, `runner`, `report`): pure-stdlib AST lint —
    host syncs on traced values, recompile triggers, donation misuse, import-
    time jit. Importing these never touches jax, so ``accelerate-tpu analyze``
    runs on lint-only CI boxes with no accelerator stack.
  - **Runtime** (`trace_guard`): `TraceGuard` counts jit cache misses per
    executable and arms ``jax.transfer_guard`` around steady-state steps.
    Imported lazily (via module ``__getattr__``) so the static half stays
    jax-free.
"""

from .linter import analyze_source
from .report import Finding, count_by_severity, render_json, render_text, worst_severity
from .rules import RULES, RULES_BY_ID, RULES_BY_SLUG, SEVERITIES, Rule, resolve_rule, severity_at_least
from .runner import analyze_paths, iter_python_files

_LAZY_RUNTIME = ("TraceGuard", "TraceGuardViolation", "TraceReport")


def __getattr__(name):
    if name in _LAZY_RUNTIME:
        from . import trace_guard

        return getattr(trace_guard, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Rule",
    "RULES",
    "RULES_BY_ID",
    "RULES_BY_SLUG",
    "SEVERITIES",
    "Finding",
    "analyze_source",
    "analyze_paths",
    "iter_python_files",
    "count_by_severity",
    "render_text",
    "render_json",
    "worst_severity",
    "resolve_rule",
    "severity_at_least",
    "TraceGuard",
    "TraceGuardViolation",
    "TraceReport",
]

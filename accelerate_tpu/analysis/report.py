"""Findings and their renderings (text for humans, JSON for CI).

Kept free of any jax import: `accelerate-tpu analyze` must run on a machine
with no accelerator stack at all (pre-merge CI lint boxes).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Sequence

from .rules import RULES_BY_ID, SEVERITIES, severity_at_least

#: Schema version stamped into --json output so downstream consumers can detect
#: format drift.
JSON_VERSION = 1


@dataclass(frozen=True)
class Finding:
    """One hazard at one source location."""

    file: str
    line: int
    col: int
    rule_id: str
    message: str

    @property
    def rule(self):
        return RULES_BY_ID[self.rule_id]

    @property
    def severity(self) -> str:
        return self.rule.severity

    def to_dict(self) -> Dict:
        return {
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "rule": self.rule_id,
            "slug": self.rule.slug,
            "severity": self.severity,
            "message": self.message,
            "fixit": self.rule.fixit,
        }


def count_by_severity(findings: Sequence[Finding]) -> Dict[str, int]:
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] += 1
    return counts


def worst_severity(findings: Sequence[Finding]):
    worst = None
    for f in findings:
        if worst is None or severity_at_least(f.severity, worst):
            worst = f.severity
    return worst


def render_text(findings: Sequence[Finding], files_scanned: int) -> str:
    """Compiler-style one-line-per-finding report plus a summary footer."""
    lines: List[str] = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.col, f.rule_id)):
        lines.append(f"{f.file}:{f.line}:{f.col}: {f.severity} {f.rule_id} [{f.rule.slug}] {f.message}")
        lines.append(f"    fixit: {f.rule.fixit}")
    counts = count_by_severity(findings)
    lines.append(
        f"{files_scanned} file(s) scanned: "
        f"{counts['error']} error(s), {counts['warn']} warning(s), {counts['info']} info"
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], files_scanned: int) -> str:
    payload = {
        "version": JSON_VERSION,
        "files_scanned": files_scanned,
        "counts": count_by_severity(findings),
        "findings": [
            f.to_dict()
            for f in sorted(findings, key=lambda f: (f.file, f.line, f.col, f.rule_id))
        ],
    }
    return json.dumps(payload, indent=2)

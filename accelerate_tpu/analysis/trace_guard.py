"""Runtime half of `accelerate analyze`: prove the no-recompile / no-host-sync
discipline actually holds on a live step.

`TraceGuard` is a (re-entrant) context manager that, while armed:

  - **counts jit cache misses per executable** — jax has no public compile
    hook, but with ``jax_log_compiles`` enabled every cache miss logs
    ``"Compiling <name> with global shapes..."`` from the pxla internals; a
    logging handler on that logger gives us a per-executable miss ledger
    (cross-checked by a `jax.monitoring` backend-compile event counter, which
    carries no name but survives log-format drift);
  - **arms ``jax.transfer_guard``** (default ``"disallow"``) so accidental
    *implicit* transfers — raw numpy leaking into a jitted call, an implicit
    ``bool()`` on a device value — raise at the offending line, while the
    sanctioned explicit step-boundary pattern (``jnp.asarray`` operand pushes,
    ``np.asarray``/``.item()`` drains) passes untouched. That asymmetry is the
    whole point: the guard encodes the repo's host discipline, not "no
    transfers ever".

On exit, ``on_violation="raise"`` turns any observed cache miss into a
`TraceGuardViolation` naming the recompiled executables; ``"record"`` just
keeps the ledger (bench integration reads it into the result JSON).

Steady-state is the caller's business: arm the guard AFTER warmup (every
program compiles once, by design). `TraceGuard.wrap(step_fn, warmup=1)` does
that bookkeeping for per-call arming — `Accelerator(analyze=True)` uses it to
watch the fused train step.
"""

from __future__ import annotations

import contextlib
import logging
import re
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_COMPILE_LOG_RE = re.compile(r"Compiling ([^\s]+) with global shapes")
_TRANSFER_RE = re.compile(
    r"Disallowed (host-to-device|device-to-host|device-to-device) transfer"
)
#: The logger jax's executable build path logs "Compiling <name> ..." on.
_PXLA_LOGGER = "jax._src.interpreters.pxla"

# jax.monitoring listeners cannot be unregistered individually, so a single
# module-level listener fans out to whatever guards are currently armed.
_ARMED_GUARDS: List["TraceGuard"] = []
_LISTENER_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def _ensure_monitoring_listener():
    global _LISTENER_INSTALLED
    with _LISTENER_LOCK:
        if _LISTENER_INSTALLED:
            return
        import jax.monitoring

        def on_duration(event: str, duration: float, **kwargs):
            if event == "/jax/core/compile/backend_compile_duration":
                for guard in list(_ARMED_GUARDS):
                    guard.backend_compiles += 1

        jax.monitoring.register_event_duration_secs_listener(on_duration)
        _LISTENER_INSTALLED = True


class TraceGuardViolation(RuntimeError):
    """A steady-state step recompiled (or the wrapped step saw a guarded
    transfer). Carries the report so CI output names the executable."""

    def __init__(self, message: str, report: "TraceReport"):
        super().__init__(message)
        self.report = report


@dataclass
class TraceReport:
    """What one armed window observed."""

    compiles: Dict[str, int] = field(default_factory=dict)  # executable -> misses
    backend_compiles: int = 0
    transfer_violations: List[str] = field(default_factory=list)
    steps: int = 0

    @property
    def total_recompiles(self) -> int:
        # The named ledger is primary; the monitoring counter catches misses
        # whose log line we failed to parse (format drift across jax versions).
        return max(sum(self.compiles.values()), self.backend_compiles)

    @property
    def host_transfers(self) -> int:
        return len(self.transfer_violations)

    def summary(self) -> str:
        if not self.compiles and not self.backend_compiles and not self.transfer_violations:
            return "clean: 0 recompiles, 0 guarded transfers"
        parts = []
        if self.compiles:
            named = ", ".join(f"{name} x{n}" for name, n in sorted(self.compiles.items()))
            parts.append(f"recompiled: {named}")
        elif self.backend_compiles:
            parts.append(f"{self.backend_compiles} backend compile(s) (unnamed)")
        if self.transfer_violations:
            parts.append(f"{len(self.transfer_violations)} guarded transfer(s)")
        return "; ".join(parts)


class _CompileLogHandler(logging.Handler):
    def __init__(self, guard: "TraceGuard"):
        super().__init__(level=logging.DEBUG)
        self._guard = guard

    def emit(self, record: logging.LogRecord):
        try:
            message = record.getMessage()
        except Exception:  # noqa: BLE001 — never let telemetry break the step
            return
        m = _COMPILE_LOG_RE.search(message)
        if m:
            name = m.group(1)
            self._guard.compiles[name] = self._guard.compiles.get(name, 0) + 1


class TraceGuard:
    """Armed window asserting "this code neither recompiles nor host-syncs".

    Parameters:
      - ``transfer_guard``: jax transfer-guard level while armed ("disallow" by
        default; "log" to only trace, None to leave transfers unguarded).
      - ``on_violation``: "raise" — exit raises `TraceGuardViolation` when any
        cache miss was observed; "record" — only keep the ledger.
      - ``name``: label used in violation messages.

    The per-window counters (`compiles`, `transfer_violations`, `steps`)
    accumulate across re-entries until `reset()`.
    """

    def __init__(
        self,
        transfer_guard: Optional[str] = "disallow",
        on_violation: str = "raise",
        name: str = "trace-guard",
        guard_device_to_device: bool = False,
    ):
        if on_violation not in ("raise", "record"):
            raise ValueError("on_violation must be 'raise' or 'record'")
        self.transfer_guard = transfer_guard
        # d2d is OFF by default: replicating an uncommitted scalar operand
        # across the mesh at dispatch is routine GSPMD placement, not a host
        # sync — guarding it would flag every sharded train step.
        self.guard_device_to_device = guard_device_to_device
        self.on_violation = on_violation
        self.name = name
        self.compiles: Dict[str, int] = {}
        self.backend_compiles = 0
        self.transfer_violations: List[str] = []
        self.steps = 0
        self._depth = 0
        self._stack: Optional[contextlib.ExitStack] = None
        self._handler: Optional[_CompileLogHandler] = None
        self._saved_log_compiles = None
        self._saved_propagate = True
        self._saved_dispatch_level = logging.NOTSET

    # ------------------------------------------------------------------ arming
    def __enter__(self) -> "TraceGuard":
        self._depth += 1
        if self._depth > 1:
            return self
        import jax

        _ensure_monitoring_listener()
        _ARMED_GUARDS.append(self)
        self._saved_log_compiles = bool(jax.config.jax_log_compiles)
        pxla_logger = logging.getLogger(_PXLA_LOGGER)
        if not self._saved_log_compiles:
            jax.config.update("jax_log_compiles", True)
            # We turned the compile logs on for OUR handler only — keep them
            # out of the user's stderr (restored on exit). If the user had
            # jax_log_compiles on already, their logging setup is respected.
            self._saved_propagate = pxla_logger.propagate
            pxla_logger.propagate = False
            dispatch_logger = logging.getLogger("jax._src.dispatch")
            self._saved_dispatch_level = dispatch_logger.level
            dispatch_logger.setLevel(logging.ERROR)
        self._handler = _CompileLogHandler(self)
        pxla_logger.addHandler(self._handler)
        self._stack = contextlib.ExitStack()
        if self.transfer_guard is not None:
            self._stack.enter_context(jax.transfer_guard_host_to_device(self.transfer_guard))
            self._stack.enter_context(jax.transfer_guard_device_to_host(self.transfer_guard))
            if self.guard_device_to_device:
                self._stack.enter_context(jax.transfer_guard_device_to_device(self.transfer_guard))
        return self

    def __exit__(self, exc_type, exc, tb):
        self._depth -= 1
        if self._depth > 0:
            return False
        import jax

        # Disarm from the monitoring fan-out FIRST: compiles outside the armed
        # window must not reach this guard's ledger (and per-step re-arming
        # must not grow the list).
        try:
            _ARMED_GUARDS.remove(self)
        except ValueError:
            pass
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        if self._handler is not None:
            logging.getLogger(_PXLA_LOGGER).removeHandler(self._handler)
            self._handler = None
        if self._saved_log_compiles is False:
            jax.config.update("jax_log_compiles", False)
            logging.getLogger(_PXLA_LOGGER).propagate = self._saved_propagate
            logging.getLogger("jax._src.dispatch").setLevel(self._saved_dispatch_level)
        self._saved_log_compiles = None
        if exc is not None:
            # An in-flight exception (possibly a transfer violation) wins;
            # record it on the way out but don't mask it.
            self.observe(exc)
            return False
        if self.on_violation == "raise" and self.report().total_recompiles:
            raise TraceGuardViolation(
                f"[{self.name}] steady-state step recompiled — {self.report().summary()}",
                self.report(),
            )
        return False

    # ------------------------------------------------------------------ ledger
    def reset(self):
        self.compiles = {}
        self.backend_compiles = 0
        self.transfer_violations = []
        self.steps = 0

    def report(self) -> TraceReport:
        return TraceReport(
            compiles=dict(self.compiles),
            backend_compiles=self.backend_compiles,
            transfer_violations=list(self.transfer_violations),
            steps=self.steps,
        )

    @property
    def total_recompiles(self) -> int:
        return self.report().total_recompiles

    @property
    def host_transfers(self) -> int:
        return len(self.transfer_violations)

    # ----------------------------------------------------------------- helpers
    @staticmethod
    def is_transfer_violation(exc: BaseException) -> bool:
        """Does this exception come from an armed jax transfer guard?"""
        return bool(_TRANSFER_RE.search(str(exc)))

    def observe(self, exc: BaseException) -> bool:
        """Record `exc` if it is a guarded-transfer error (serving's fault
        isolation calls this before swallowing a step exception, so swallowed
        violations still reach the ledger). Returns True when recorded."""
        if self.is_transfer_violation(exc):
            self.transfer_violations.append(str(exc).splitlines()[0][:200])
            return True
        return False

    def wrap(self, fn: Callable, warmup: int = 1) -> Callable:
        """Per-call arming with a warmup allowance: the first `warmup` calls
        run unguarded (compiles are expected), every later call runs inside the
        armed guard — so call N+1 onward raising means a *steady-state*
        recompile, reported with the executable's name."""

        state = {"calls": 0}

        def guarded(*args, **kwargs):
            state["calls"] += 1
            if state["calls"] <= warmup:
                return fn(*args, **kwargs)
            with self:
                # In-flight exceptions (including guarded transfers) are
                # observe()d once by __exit__ on the way out.
                self.steps += 1
                return fn(*args, **kwargs)

        guarded.__wrapped__ = fn  # type: ignore[attr-defined]
        guarded.trace_guard = self  # type: ignore[attr-defined]
        return guarded

"""Filesystem front-end for the linter: expand paths, lint every ``.py`` file,
aggregate findings. No jax import — `accelerate-tpu analyze` stays runnable on
lint-only CI boxes."""

from __future__ import annotations

import os
from typing import Iterable, List, Sequence, Tuple

from .linter import analyze_source
from .report import Finding

#: Directory names never worth descending into.
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", ".venv", "node_modules", "build", "dist"}


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        else:
            raise FileNotFoundError(f"no such file or directory: {path}")


def analyze_paths(paths: Sequence[str]) -> Tuple[List[Finding], int]:
    """Lint every Python file under `paths` -> (findings, files_scanned).
    Unreadable/undecodable files are skipped (count still reflects scanned)."""
    findings: List[Finding] = []
    scanned = 0
    for file_path in iter_python_files(paths):
        try:
            with open(file_path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        scanned += 1
        findings.extend(analyze_source(source, file_path))
    return findings, scanned

"""AST-based TPU-hazard linter (stdlib `ast` only — no jax import, so the lint
runs on CI boxes with no accelerator stack).

The pass is module-local and two-phase:

  1. **Index**: resolve import aliases (``jax``, ``jnp``, ``np``, bare ``jit``/
     ``pjit``), find every *jit root* — a function jitted by decorator, by a
     ``jax.jit(fn)`` reference, or handed to ``jax.lax`` control flow — then
     close over module-local calls and nested defs to get the **jit-reachable**
     set. Code outside that set is host code, where ``np.asarray``/``float()``
     at step boundaries is the sanctioned discipline, not a hazard.
  2. **Check**: walk each function with per-rule detectors (see `rules.py` for
     the catalog). Traced-value tracking is a deliberately simple fixpoint over
     assignments: a function parameter or anything computed from ``jnp``/
     ``jax`` calls is traced; ``.shape``/``.ndim``/``.dtype`` projections are
     static and exempt.

Suppressions: a ``# tpu-lint: disable=<rule-id>[,<rule-id>]`` comment on the
flagged line drops those findings (``all`` drops every rule); a
``# tpu-lint: disable-file=<rule-id>`` comment anywhere silences the rule for
the whole file. Unknown tokens are ignored rather than fatal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .report import Finding
from .rules import resolve_rule

#: Array methods whose *call* on a traced value yields a traced value that a
#: Python branch would then implicitly bool() (``if x.any():``).
ARRAY_TEST_METHODS = {"any", "all", "sum", "max", "min", "mean", "prod"}
#: Static projections of an array — branching on these is shape-level Python
#: and perfectly jit-safe.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
#: ``jax.lax`` combinators whose function-valued arguments get traced.
LAX_TRACED_FN_CONSUMERS = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "map", "associative_scan",
}
#: The tracing API surface (`telemetry.tracing`): calls whose arguments are
#: span annotations, and whose `with` blocks wrap hot-path dispatches.
SPAN_API_ATTRS = {"span", "start_span", "event", "annotate"}
#: Blocking checkpoint-I/O entry points (`accelerate_tpu.checkpointing` + the
#: Accelerator facade): serialize/fsync/digest work that must never run inside
#: a traced program (rule TPU113). Matched as a bare name or the final
#: attribute of a call chain (`accelerator.save_state(...)`, `mgr.save(...)`
#: is deliberately NOT here — `.save` alone is too generic).
CHECKPOINT_IO_CALLS = {
    "save_pytree",
    "save_pytree_host_shards",
    "save_pytree_shards",
    "save_accelerator_state",
    "write_accelerator_snapshot",
    "save_state",
    "load_state",
    "atomic_write",
    "atomic_write_bytes",
    "atomic_write_json",
    "file_sha256",
    "write_checkpoint_manifest",
    "save_custom_state",
}

_SUPPRESS_LINE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")
_SUPPRESS_FILE = re.compile(r"#\s*tpu-lint:\s*disable-file=([A-Za-z0-9_,\- ]+)")


def _parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> ({line: {rule ids}}, {file-wide rule ids}); tokens resolve via id or
    slug, ``all`` means every rule."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()

    def resolve_tokens(blob: str) -> Set[str]:
        out: Set[str] = set()
        for token in blob.split(","):
            token = token.strip()
            if not token:
                continue
            if token.lower() == "all":
                out.add("all")
                continue
            rule = resolve_rule(token)
            if rule is not None:
                out.add(rule.id)
        return out

    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_FILE.search(line)
        if m:
            file_wide |= resolve_tokens(m.group(1))
            continue
        m = _SUPPRESS_LINE.search(line)
        if m:
            tokens = resolve_tokens(m.group(1))
            per_line.setdefault(lineno, set()).update(tokens)
            if line.strip().startswith("#"):
                # A standalone suppression comment covers the next line too
                # (the statement it annotates).
                per_line.setdefault(lineno + 1, set()).update(tokens)
    return per_line, file_wide


class _ModuleIndex:
    """Import aliases + function defs + the jit-reachable set for one module."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.jax_aliases: Set[str] = set()
        #: A REAL jax/jax.numpy import was seen (the conventional jnp/np
        #: fallbacks below don't count): the "jit-adjacent module" signal
        #: rules like TPU114 scope themselves to.
        self.imports_jax = False
        #: A flax import was seen: the "model module" signal TPU119 scopes
        #: itself to (sharding-rule tables ship next to the flax modules
        #: whose parameter paths they must match).
        self.imports_flax = False
        self.jnp_aliases: Set[str] = set()
        self.np_aliases: Set[str] = set()
        self.lax_aliases: Set[str] = set()
        self.jit_names: Set[str] = set()  # bare names bound to jax.jit / pjit
        self.pjit_names: Set[str] = set()
        self.partial_names: Set[str] = set()
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.jit_calls: List[ast.Call] = []  # every jax.jit / pjit invocation

        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                child._tpu_parent = parent  # type: ignore[attr-defined]

        self._collect_imports()
        self._collect_defs()
        self.jit_roots = self._find_jit_roots()
        self.reachable = self._close_reachability(self.jit_roots)

    # -- indexing ---------------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name, bound = alias.name, alias.asname or alias.name.split(".")[0]
                    if name == "jax":
                        self.jax_aliases.add(bound)
                        self.imports_jax = True
                    elif name in ("jax.numpy",):
                        self.jnp_aliases.add(alias.asname or "jax")
                        self.imports_jax = True
                    elif name in ("numpy",):
                        self.np_aliases.add(bound)
                    elif name == "flax" or name.startswith("flax."):
                        self.imports_flax = True
                    elif name == "functools":
                        pass
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax" or mod.startswith("jax."):
                    self.imports_jax = True
                if mod == "flax" or mod.startswith("flax."):
                    self.imports_flax = True
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if mod == "jax" and alias.name == "numpy":
                        self.jnp_aliases.add(bound)
                    elif mod == "jax" and alias.name == "jit":
                        self.jit_names.add(bound)
                    elif mod == "jax" and alias.name == "lax":
                        self.lax_aliases.add(bound)
                    elif alias.name == "pjit" and "pjit" in mod:
                        self.pjit_names.add(bound)
                    elif mod == "functools" and alias.name == "partial":
                        self.partial_names.add(bound)
        # Conventional fallbacks: most sources spell these jnp/np even when the
        # import is renamed out of our sight (e.g. injected globals in fixtures).
        self.jnp_aliases.add("jnp")
        self.np_aliases.add("np")

    def _collect_defs(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)

    # -- alias predicates -------------------------------------------------------
    def _attr_root(self, node: ast.AST) -> Optional[List[str]]:
        """Attribute/Name chain -> ['jax', 'lax', 'scan'] (None if not a chain)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return list(reversed(parts))
        return None

    def is_jit_func(self, node: ast.AST) -> bool:
        """Does this expression denote jax.jit (or pjit)?"""
        chain = self._attr_root(node)
        if chain is None:
            return False
        if len(chain) == 1:
            return chain[0] in self.jit_names or chain[0] in self.pjit_names
        if chain[0] in self.jax_aliases and chain[-1] in ("jit", "pjit"):
            return True
        return chain[-1] == "pjit"  # pjit.pjit / experimental chains

    def is_pjit_func(self, node: ast.AST) -> bool:
        chain = self._attr_root(node)
        if chain is None:
            return False
        return chain[-1] == "pjit" or (len(chain) == 1 and chain[0] in self.pjit_names)

    def is_jnp_rooted(self, node: ast.AST) -> bool:
        chain = self._attr_root(node)
        return bool(chain) and (chain[0] in self.jnp_aliases or chain[0] in self.jax_aliases or chain[0] in self.lax_aliases)

    def is_np_rooted(self, node: ast.AST) -> bool:
        chain = self._attr_root(node)
        return bool(chain) and chain[0] in self.np_aliases

    # -- jit roots & reachability ----------------------------------------------
    def _jit_target_of_call(self, call: ast.Call) -> Optional[str]:
        """`jax.jit(fn, ...)` / `partial(jax.jit, ...)` -> 'fn' when it's a bare
        Name that resolves to a module-local def."""
        func = call.func
        is_jit = self.is_jit_func(func)
        if not is_jit and isinstance(func, ast.Call):
            # partial(jax.jit, ...) applied later — the partial call IS the jit.
            inner = func
            if (
                isinstance(inner.func, ast.Name)
                and inner.func.id in self.partial_names
                and inner.args
                and self.is_jit_func(inner.args[0])
            ):
                is_jit = True
        if not is_jit:
            return None
        if call.args and isinstance(call.args[0], ast.Name):
            return call.args[0].id
        return None

    def _find_jit_roots(self) -> Set[ast.AST]:
        roots: Set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self.is_jit_func(dec):
                        roots.add(node)
                    elif isinstance(dec, ast.Call):
                        if self.is_jit_func(dec.func):
                            roots.add(node)
                        elif (
                            isinstance(dec.func, ast.Name)
                            and dec.func.id in self.partial_names
                            and dec.args
                            and self.is_jit_func(dec.args[0])
                        ):
                            roots.add(node)
            elif isinstance(node, ast.Call):
                if self.is_jit_func(node.func) or (
                    isinstance(node.func, ast.Call) and self._jit_target_of_call(node) is not None
                ):
                    self.jit_calls.append(node)
                    target = self._jit_target_of_call(node)
                    if target and target in self.defs_by_name:
                        roots.update(self.defs_by_name[target])
                else:
                    chain = self._attr_root(node.func)
                    if (
                        chain
                        and chain[-1] in LAX_TRACED_FN_CONSUMERS
                        and (chain[0] in self.jax_aliases or chain[0] in self.lax_aliases)
                    ):
                        for arg in node.args:
                            if isinstance(arg, ast.Name) and arg.id in self.defs_by_name:
                                roots.update(self.defs_by_name[arg.id])
        return roots

    def _close_reachability(self, roots: Set[ast.AST]) -> Set[ast.AST]:
        """Roots + nested defs + module-local functions they call, to fixpoint."""
        reachable = set(roots)
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                new: List[ast.AST] = []
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                    new.append(node)
                elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    new.extend(self.defs_by_name.get(node.func.id, ()))
                for cand in new:
                    if cand not in reachable:
                        reachable.add(cand)
                        frontier.append(cand)
        return reachable


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "_tpu_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_tpu_parent", None)
    return None


def _enclosing_loop(node: ast.AST, stop_at: Optional[ast.AST] = None) -> Optional[ast.AST]:
    cur = getattr(node, "_tpu_parent", None)
    while cur is not None and cur is not stop_at:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None  # a nested def is a new host frame, not "inside the loop"
        cur = getattr(cur, "_tpu_parent", None)
    return None


#: Annotation spellings that declare a parameter host-static: a `use_scaler:
#: bool` or `k: int` param is a trace-time constant, not a traced array.
_STATIC_ANNOTATION = re.compile(
    r"^(?:typing\.)?(?:Optional\[)?(?:bool|int|float|str|bytes)\]?$"
)


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = []
    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        if p.annotation is not None:
            try:
                if _STATIC_ANNOTATION.match(ast.unparse(p.annotation)):
                    continue
            except Exception:  # noqa: BLE001 — exotic annotation, assume traced
                pass
        names.append(p.arg)
    if a.vararg:
        names.append(a.vararg.arg)
    return {n for n in names if n not in ("self", "cls")}


class _FunctionChecker:
    """Per-function rule evaluation. `jit_reachable` switches between the
    traced-code rule set (TPU101-104) and the host-loop rule (TPU111)."""

    def __init__(self, index: _ModuleIndex, fn: ast.AST, path: str):
        self.index = index
        self.fn = fn
        self.path = path
        self.findings: List[Finding] = []
        self.traced: Set[str] = _param_names(fn)
        self._infer_traced_locals()

    def emit(self, node: ast.AST, rule_id: str, message: str):
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, rule_id, message)
        )

    # -- traced-name inference --------------------------------------------------
    def _direct_statements(self):
        """Statements belonging to this function, excluding nested defs (their
        params are their own frame's business)."""
        for node in ast.walk(self.fn):
            owner = _enclosing_function(node) if node is not self.fn else self.fn
            if owner is self.fn:
                yield node

    def _infer_traced_locals(self):
        for _ in range(2):  # tiny fixpoint: handles one level of chained assigns
            for node in self._direct_statements():
                if isinstance(node, ast.Assign) and self._is_traced_expr(node.value):
                    for tgt in node.targets:
                        for name in ast.walk(tgt):
                            if isinstance(name, ast.Name):
                                self.traced.add(name.id)

    def _is_traced_expr(self, node: ast.AST) -> bool:
        """Does evaluating this expression yield (or require syncing) a traced
        array? Static projections (.shape and friends), `is None` tests and
        len() stay host-side."""
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return False  # plain attribute access (config.do_sample) is host data
        if isinstance(node, ast.Subscript):
            return self._is_traced_expr(node.value)
        if isinstance(node, ast.Call):
            func = node.func
            if self.index.is_jnp_rooted(func):
                return True
            if isinstance(func, ast.Attribute) and func.attr in ARRAY_TEST_METHODS:
                return self._is_traced_expr(func.value)
            return False
        if isinstance(node, ast.UnaryOp):
            return self._is_traced_expr(node.operand)
        if isinstance(node, ast.BinOp):
            return self._is_traced_expr(node.left) or self._is_traced_expr(node.right)
        if isinstance(node, ast.BoolOp):
            return any(self._is_traced_expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self._is_traced_expr(node.left) or any(
                self._is_traced_expr(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self._is_traced_expr(node.body) or self._is_traced_expr(node.orelse)
        return False

    # -- jit-reachable rules ----------------------------------------------------
    def check_traced_rules(self):
        for node in self._direct_statements():
            if isinstance(node, ast.Call):
                self._check_item(node)
                self._check_scalar_cast(node)
                self._check_numpy_transfer(node)
                self._check_checkpoint_io(node)
            elif isinstance(node, (ast.If, ast.While)):
                if self._is_traced_expr(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    self.emit(
                        node,
                        "TPU104",
                        f"`{kind}` on a traced value implicitly calls bool() — a "
                        "host sync that fails under jit; use jnp.where/lax.cond",
                    )

    def _check_item(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" and not node.args:
            self.emit(
                node,
                "TPU101",
                ".item() inside jit-reachable code syncs the device and fails "
                "under tracing",
            )

    def _check_checkpoint_io(self, node: ast.Call):
        """TPU113: blocking checkpoint I/O in jit-reachable code. Serialize +
        fsync under trace is a host sync when it works and a tracer leak when
        it doesn't; checkpoints belong at step boundaries (async_save moves
        even the boundary cost to a background committer)."""
        func = node.func
        name = None
        if isinstance(func, ast.Name) and func.id in CHECKPOINT_IO_CALLS:
            name = func.id
        elif isinstance(func, ast.Attribute) and func.attr in CHECKPOINT_IO_CALLS:
            name = func.attr
        if name is not None:
            self.emit(
                node,
                "TPU113",
                f"{name}() is blocking checkpoint I/O inside jit-reachable code — "
                "checkpoint from host code at the step boundary (async_save commits "
                "in the background)",
            )

    def _check_scalar_cast(self, node: ast.Call):
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and self._is_traced_expr(node.args[0])
        ):
            self.emit(
                node,
                "TPU102",
                f"{node.func.id}() on a traced value is a host sync (and a "
                "TracerConversionError under jit)",
            )

    def _check_numpy_transfer(self, node: ast.Call):
        func = node.func
        chain = self.index._attr_root(func)
        if chain is None:
            return
        if (
            len(chain) >= 2
            and chain[0] in self.index.np_aliases
            and chain[-1] in ("asarray", "array")
            and node.args
            and self._is_traced_expr(node.args[0])
        ):
            self.emit(
                node,
                "TPU103",
                f"{'.'.join(chain)}() on a traced value forces a device-to-host "
                "copy inside the program",
            )
        elif chain[0] in self.index.jax_aliases and chain[-1] == "device_get":
            self.emit(
                node,
                "TPU103",
                "jax.device_get inside jit-reachable code is a host transfer; "
                "return the value and read it at the step boundary",
            )

    # -- host-side rules --------------------------------------------------------
    def check_host_loop_syncs(self):
        """TPU111: float()/.item() on a value produced by a call in the SAME
        loop — the per-step logging sync that serializes dispatch."""
        for loop in self._direct_statements():
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            stepped: Set[str] = set()
            for node in ast.walk(loop):
                if _enclosing_loop(node, stop_at=self.fn) is not loop:
                    continue
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    for tgt in node.targets:
                        for name in ast.walk(tgt):
                            if isinstance(name, ast.Name):
                                stepped.add(name.id)
            for node in ast.walk(loop):
                if _enclosing_loop(node, stop_at=self.fn) is not loop:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                synced = None
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "float"
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in stepped
                ):
                    synced = f"float({node.args[0].id})"
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in stepped
                ):
                    synced = f"{node.func.value.id}.item()"
                if synced:
                    self.emit(
                        node,
                        "TPU111",
                        f"{synced} every loop iteration blocks on the device; "
                        "accumulate on device and read once per epoch",
                    )

    # -- tracer instrumentation (TPU112) ----------------------------------------
    def _device_derived_names(self) -> Set[str]:
        """Names assigned from jnp/jax-rooted calls: device arrays living in
        HOST code — perfectly legal until something reads them synchronously.
        (Deliberately excludes parameters and opaque calls: host code reading
        back its OWN dispatch outputs at the step boundary is the sanctioned
        discipline, not a hazard.)"""
        device: Set[str] = set()
        for _ in range(2):  # tiny fixpoint, like _infer_traced_locals
            for node in self._direct_statements():
                if isinstance(node, ast.Assign) and self._is_device_expr(node.value, device):
                    for tgt in node.targets:
                        for name in ast.walk(tgt):
                            if isinstance(name, ast.Name):
                                device.add(name.id)
        return device

    def _is_device_expr(self, node: ast.AST, device: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in device
        if isinstance(node, ast.Attribute):
            return False  # .shape/.dtype and host attributes alike
        if isinstance(node, ast.Subscript):
            return self._is_device_expr(node.value, device)
        if isinstance(node, ast.Call):
            func = node.func
            if self.index.is_jnp_rooted(func):
                return True
            if isinstance(func, ast.Attribute) and func.attr in ARRAY_TEST_METHODS:
                return self._is_device_expr(func.value, device)
            return False
        if isinstance(node, ast.BinOp):
            return self._is_device_expr(node.left, device) or self._is_device_expr(
                node.right, device
            )
        if isinstance(node, ast.UnaryOp):
            return self._is_device_expr(node.operand, device)
        return False

    def _device_read(self, node: ast.AST, device: Set[str]) -> Optional[str]:
        """A call that synchronously pulls a device value to host — `.item()`,
        `float()/int()/bool()`, `np.asarray`/`np.array`, `jax.device_get` — of
        a device-derived expression. Returns its spelling, or None."""
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "item"
            and not node.args
            and self._is_device_expr(func.value, device)
        ):
            return ".item()"
        if (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and self._is_device_expr(node.args[0], device)
        ):
            return f"{func.id}()"
        chain = self.index._attr_root(func)
        if chain and node.args and self._is_device_expr(node.args[0], device):
            if chain[0] in self.index.np_aliases and chain[-1] in ("asarray", "array"):
                return f"{'.'.join(chain)}()"
            if chain[0] in self.index.jax_aliases and chain[-1] == "device_get":
                return "jax.device_get()"
        return None

    @staticmethod
    def _is_span_api_call(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in SPAN_API_ATTRS
        )

    def check_span_hazards(self):
        """TPU112: instrumentation can never reintroduce a host sync. Flags a
        device-value read feeding a span/event annotation, a device array
        passed as an annotation outright, and synchronous device reads sitting
        inside a `with ...span(...)` block (where they would serialize the
        very dispatch the span is timing)."""
        device = self._device_derived_names()
        flagged: Set[int] = set()

        def flag(node: ast.AST, what: str, where: str):
            if id(node) in flagged:
                return
            flagged.add(id(node))
            self.emit(
                node,
                "TPU112",
                f"{what} {where} hides a blocking device sync in the "
                "instrumentation — read at the step boundary, annotate with the "
                "host scalar",
            )

        for node in self._direct_statements():
            if self._is_span_api_call(node):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    read = self._device_read(arg, device)
                    if read is not None:
                        flag(arg, read, "in a span annotation")
                    elif self._is_device_expr(arg, device):
                        flag(arg, "a device array", "as a span annotation")
            elif isinstance(node, ast.With) and any(
                self._is_span_api_call(item.context_expr) for item in node.items
            ):
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        read = self._device_read(sub, device)
                        if read is not None:
                            flag(sub, read, "inside a `with ...span(...)` block")


class _ModuleChecker:
    """Module-scope rules: jit-in-loop, static_argnums misuse, donated reuse,
    import-time jit, pjit annotations, closure scalar capture."""

    def __init__(self, index: _ModuleIndex, path: str):
        self.index = index
        self.path = path
        self.findings: List[Finding] = []

    def emit(self, node: ast.AST, rule_id: str, message: str):
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, rule_id, message)
        )

    def run(self):
        self._check_jit_placement()
        self._check_pjit_annotations()
        self._check_static_argnums_and_donation()
        self._check_closure_capture()
        self._check_serving_construction()
        self._check_kernel_fallback()
        self._check_tp_replicated_operand()
        self._check_replicated_optimizer_state()
        self._check_host_hop_in_stage_handoff()
        self._check_worker_loop()
        self._check_unbounded_reconnect()
        self._check_quantization()
        self._check_dead_partition_rule()
        return self.findings

    # -- quantized serving (TPU117) ----------------------------------------------
    #: Serving attention/kernel seams whose scale arguments must be traced
    #: arrays (the pool's parallel scale pools), never Python scalars.
    _QUANT_SCALE_FUNCS = {
        "paged_decode_attention",
        "paged_verify_attention",
        "slot_cache_attention",
        "update_slot_cache",
        "quantized_pool_write",
        "dequantize_kv",
        "quantize_kv",
    }
    _QUANT_SCALE_KWARGS = {"k_scale", "v_scale"}
    #: KV cache dtype knobs and their one legal value set
    #: (ops/quantization.KV_CACHE_DTYPES; duplicated as literals so the
    #: linter stays stdlib-only with no jax import).
    _KV_DTYPE_KWARGS = {"kv_cache_dtype", "decode_kv_cache_dtype"}
    _KV_DTYPES_OK = {"bf16", "int8", "fp8_e4m3"}

    def _check_quantization(self):
        """TPU117: quantization knobs that silently break the compiled-once
        discipline or fail late. (a) A scale passed as a Python NUMERIC
        LITERAL to a serving attention/kernel seam is baked into the
        executable at trace time — the scale pool exists precisely so scale
        changes ride as operands; one hard-coded float either pins every page
        to one scale or retraces per value. (b) A `kv_cache_dtype` /
        `decode_kv_cache_dtype` string literal off the supported set fails at
        engine construction at best — flag it where it's written, not where
        it detonates."""
        if not self.index.imports_jax:
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                value = kw.value
                if (
                    kw.arg in self._QUANT_SCALE_KWARGS
                    and name in self._QUANT_SCALE_FUNCS
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, (int, float))
                    and not isinstance(value.value, bool)
                ):
                    self.emit(
                        node,
                        "TPU117",
                        f"{name}({kw.arg}={value.value!r}) bakes a quantization "
                        "scale into the executable at trace time — pass the "
                        "pool's traced scale array (key_scale/value_scale) so "
                        "scale updates never retrace the decode program",
                    )
                if (
                    kw.arg in self._KV_DTYPE_KWARGS
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                    and value.value not in self._KV_DTYPES_OK
                ):
                    supported = ", ".join(sorted(self._KV_DTYPES_OK))
                    self.emit(
                        node,
                        "TPU117",
                        f"{kw.arg}={value.value!r} is not a supported KV cache "
                        f"dtype (expected one of: {supported}) — this fails at "
                        "engine construction; int4 packing is explicitly out of "
                        "scope (docs/limitations.md)",
                    )

    # -- subprocess worker loops (TPU116) ----------------------------------------
    #: Worker-loop entry points whose heartbeat deadline is the orphan guard.
    _WORKER_LOOP_FUNCS = {"serve_worker", "WorkerLoop"}
    #: IPC receive calls that must carry a timeout when called from a loop.
    _IPC_RECV_FUNCS = {"recv_frame", "recv_message"}

    def _check_worker_loop(self):
        """TPU116: an out-of-process serving worker is supervised through
        TIMEOUTS — the controller's step deadline detects a hung worker, the
        worker's heartbeat deadline detects a dead controller. A worker loop
        built without a heartbeat deadline leaks an orphaned process (and its
        device memory) when the controller dies; an IPC recv with no timeout
        inside a loop turns a hung peer into a hung caller, invisible to the
        health machine that exists to catch exactly that."""
        if not self.index.imports_jax:
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            if name in self._WORKER_LOOP_FUNCS:
                deadline = kwargs.get("heartbeat_deadline_s")
                if "heartbeat_deadline_s" not in kwargs or (
                    isinstance(deadline, ast.Constant) and deadline.value is None
                ):
                    self.emit(
                        node,
                        "TPU116",
                        f"{name}(...) without heartbeat_deadline_s never notices a "
                        "dead controller — the worker process (and its device "
                        "memory) leaks as an orphan; pass a deadline in seconds",
                    )
            if name in self._IPC_RECV_FUNCS and _enclosing_loop(node) is not None:
                timeout = kwargs.get("timeout_s")
                if "timeout_s" not in kwargs or (
                    isinstance(timeout, ast.Constant) and timeout.value is None
                ):
                    self.emit(
                        node,
                        "TPU116",
                        f"{name}(...) inside a loop with no timeout_s blocks forever "
                        "on a hung peer — bound every looped IPC recv so the "
                        "heartbeat machinery can observe the hang",
                    )

    # -- socket transports (TPU122) ----------------------------------------------
    #: Socket receive methods that block forever on an unarmed socket.
    _SOCKET_RECV_METHODS = {"recv", "recv_into"}

    def _check_unbounded_reconnect(self):
        """TPU122: a socket-transport protocol path is only as healthy as its
        worst-case wait. Flags, in jit-adjacent modules that import `socket`:
        (a) `socket.create_connection` dialed with no (or a None) `timeout=` —
        the connect hangs on a partitioned peer for the kernel's default,
        minutes, not the transport's budget; (b) a looped `.recv`/`.recv_into`
        with no `timeout_s=` in a module that never arms a non-None
        `settimeout` — the read blocks forever on a half-open link; (c) a
        `.reconnect(...)` driven from a loop with no `timeout_s=` — the retry
        loop has neither a per-attempt bound nor (visibly) a deadline budget,
        so a dead peer hot-loops the dial instead of escalating."""
        if not self.index.imports_jax:
            return
        imports_socket = any(
            isinstance(node, ast.Import)
            and any(alias.name == "socket" for alias in node.names)
            for node in ast.walk(self.index.tree)
        )
        if not imports_socket:
            return
        #: Any non-None settimeout anywhere in the module counts as "the
        #: module arms read deadlines" — the bound need not be adjacent to
        #: the recv (select-based framing passes the deadline separately).
        arms_settimeout = any(
            isinstance(node, ast.Call)
            and self._call_name(node.func) == "settimeout"
            and node.args
            and not (
                isinstance(node.args[0], ast.Constant)
                and node.args[0].value is None
            )
            for node in ast.walk(self.index.tree)
        )
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            if name == "create_connection":
                timeout = kwargs.get("timeout")
                if "timeout" not in kwargs or (
                    isinstance(timeout, ast.Constant) and timeout.value is None
                ):
                    self.emit(
                        node,
                        "TPU122",
                        "socket.create_connection(...) without timeout= waits "
                        "out the kernel's connect default on a partitioned peer "
                        "— dial under the transport's own deadline budget",
                    )
            elif (
                name in self._SOCKET_RECV_METHODS
                and isinstance(node.func, ast.Attribute)
                and _enclosing_loop(node) is not None
                and "timeout_s" not in kwargs
                and not arms_settimeout
            ):
                self.emit(
                    node,
                    "TPU122",
                    f".{name}(...) inside a loop on a socket that was never "
                    "given a deadline (no settimeout, no timeout_s) blocks "
                    "forever on a half-open link — arm a read deadline so the "
                    "health machinery can observe the hang",
                )
            elif (
                name == "reconnect"
                and isinstance(node.func, ast.Attribute)
                and _enclosing_loop(node) is not None
                and "timeout_s" not in kwargs
            ):
                self.emit(
                    node,
                    "TPU122",
                    ".reconnect(...) retried in a loop with no timeout_s bound "
                    "per attempt hot-loops the dial against a dead peer — give "
                    "each attempt a deadline and budget the loop "
                    "(reconnect_deadline_s) so exhaustion escalates to the "
                    "respawn path",
                )

    # -- serving-engine construction (TPU114) -----------------------------------
    #: Serving front-end constructors whose robustness knobs this rule audits.
    _SERVING_CTORS = {"ContinuousBatcher", "Router"}

    def _check_serving_construction(self):
        """TPU114: a serving engine/router built in jit-adjacent code (the
        module really imports jax) without bounded queue backpressure — or a
        Router without a default deadline — fails open under overload:
        the host queue grows without limit and a stalled replica can hold a
        request forever instead of surfacing a terminal finish_reason."""
        if not self.index.imports_jax:
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Name) and func.id in self._SERVING_CTORS:
                name = func.id
            elif isinstance(func, ast.Attribute) and func.attr in self._SERVING_CTORS:
                name = func.attr
            if name is None:
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            max_queue = kwargs.get("max_queue")
            if "max_queue" not in kwargs or (
                isinstance(max_queue, ast.Constant) and max_queue.value is None
            ):
                self.emit(
                    node,
                    "TPU114",
                    f"{name}(...) without a bounded max_queue grows the host wait "
                    "queue without limit under overload — pass max_queue=<bound> "
                    "so backpressure surfaces as QueueFull",
                )
            if name == "Router":
                deadline = kwargs.get("default_deadline_s")
                if "default_deadline_s" not in kwargs or (
                    isinstance(deadline, ast.Constant) and deadline.value is None
                ):
                    self.emit(
                        node,
                        "TPU114",
                        "Router(...) without default_deadline_s lets a request wait "
                        "forever on a stalled replica — give the fleet a default "
                        "per-request deadline",
                    )

    # -- kernel-path fallback (TPU115) -------------------------------------------
    #: Pallas attention kernel entry points whose `interpret=` knob is a
    #: CPU-test shim, never a production setting.
    _PALLAS_KERNEL_FUNCS = {
        "paged_decode_attention",
        "paged_verify_attention",
        "flash_attention",
    }
    #: Constructors/seams that accept an attention implementation flag.
    _ATTENTION_IMPL_KWARGS = {"attention_impl", "decode_attention_impl"}
    #: Call targets where paging is the DEFAULT (absent page kwargs still mean
    #: a paged engine). Everywhere else — the seam functions, config
    #: constructors — page_size defaults to 0, so an "xla" pin without page
    #: kwargs is the contiguous layout's only legal impl, not a fallback.
    _PAGED_BY_DEFAULT_CTORS = {"ContinuousBatcher", "Router"}

    @staticmethod
    def _call_name(func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None

    def _check_kernel_fallback(self):
        """TPU115: the Pallas paged-decode/block-verify kernels are the serving
        hot path; the XLA gather materializes the whole logical cache per
        dispatch and exists as the parity oracle. Flags (a) a serving
        decode/verify construction pinned to the oracle by a LITERAL
        attention_impl="xla" where the paged kernel applies (the call doesn't
        also opt out of paging), and (b) a kernel call forced into interpret
        mode with a literal interpret=True — the CPU-test shim; production
        call sites use interpret=None so the kernel compiles on TPU. Both are
        one explicit keyword away from silently serving off the kernel path."""
        if not self.index.imports_jax:
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            impl = next(
                (kwargs[k] for k in self._ATTENTION_IMPL_KWARGS if k in kwargs), None
            )
            if (
                impl is not None
                and isinstance(impl, ast.Constant)
                and impl.value == "xla"
            ):
                paged = kwargs.get("paged")
                page_size = kwargs.get("page_size") or kwargs.get("decode_page_size")
                opted_out = (
                    isinstance(paged, ast.Constant) and paged.value is False
                ) or (isinstance(page_size, ast.Constant) and page_size.value in (0, None))
                if name in self._PAGED_BY_DEFAULT_CTORS:
                    paged_applies = not opted_out
                else:
                    # Seam/config spellings default to page_size=0: paging only
                    # applies when the call really threads page geometry (and
                    # doesn't zero it out).
                    paged_applies = page_size is not None and not opted_out
                if paged_applies:
                    self.emit(
                        node,
                        "TPU115",
                        'attention_impl="xla" pins this decode/verify program to the '
                        "gather oracle (a full materialized cache copy per dispatch) "
                        'where the Pallas paged kernel applies — pass "pallas_paged", '
                        "or suppress where the oracle is deliberate",
                    )
            if name in self._PALLAS_KERNEL_FUNCS:
                interp = kwargs.get("interpret")
                if isinstance(interp, ast.Constant) and interp.value is True:
                    self.emit(
                        node,
                        "TPU115",
                        f"{name}(interpret=True) forces the Pallas interpreter — the "
                        "CPU-test shim — onto this call site; use interpret=None so "
                        "the kernel compiles on TPU (tests belong under tests/, "
                        "which the self-lint roots exclude)",
                    )

    # -- tensor-parallel replicated placement (TPU118) ---------------------------
    @classmethod
    def _mentions_model_axis(cls, node: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Constant) and sub.value == "model"
            for sub in ast.walk(node)
        )

    def _module_spans_mesh(self) -> bool:
        """True when this module builds a tensor-parallel serving mesh: a
        `serving_tp_mesh(...)` call, or a `Mesh(...)` whose axis names include
        "model" — the context in which an unsharded placement is a silent
        full replication rather than ordinary single-device code."""
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            if name == "serving_tp_mesh":
                return True
            if name == "Mesh" and any(
                self._mentions_model_axis(arg)
                for arg in list(node.args) + [kw.value for kw in node.keywords]
            ):
                return True
        return False

    @classmethod
    def _placement_is_devicey(cls, node: ast.AST) -> bool:
        """A placement expression that is a raw DEVICE (not a sharding):
        `jax.devices()[...]` / `jax.local_devices()[...]` subscripts or calls,
        or a name that spells a device. Unknown names get the benefit of the
        doubt — a precomputed shardings pytree is the sanctioned pattern."""
        if isinstance(node, ast.Subscript):
            return cls._placement_is_devicey(node.value)
        if isinstance(node, ast.Call):
            return cls._call_name(node.func) in {"devices", "local_devices"}
        if isinstance(node, (ast.Name, ast.Attribute)):
            label = node.id if isinstance(node, ast.Name) else node.attr
            return label.lower() in {"device", "dev"}
        return False

    def _check_tp_replicated_operand(self):
        """TPU118: in a module that spans a serving mesh, `device_put` with no
        sharding argument (or a raw device) lands the params/pool tree on ONE
        device — every sharded executable that consumes it then replicates the
        full tree to every chip, serving token-identically while spending N x
        the per-chip HBM the mesh exists to save. The sanctioned spellings
        carry a NamedSharding (pytree): `derive_tp_param_shardings` /
        `derive_tp_cache_shardings`, or `ContinuousBatcher(tp=N)` doing the
        placement internally."""
        if not self.index.imports_jax or not self._module_spans_mesh():
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._call_name(node.func) != "device_put":
                continue
            placement = None
            if len(node.args) >= 2:
                placement = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg in ("device", "shardings", "sharding"):
                        placement = kw.value
                        break
            missing = placement is None or (
                isinstance(placement, ast.Constant) and placement.value is None
            )
            if missing or self._placement_is_devicey(placement):
                self.emit(
                    node,
                    "TPU118",
                    "device_put without a NamedSharding in a mesh-spanning serving "
                    "module places the tree on one device and lets jit replicate it "
                    "to every chip — derive shardings from the model family's rules "
                    "(derive_tp_param_shardings / derive_tp_cache_shardings) or let "
                    "ContinuousBatcher(tp=N) place it",
                )

    # -- replicated optimizer state (TPU120) --------------------------------------
    @classmethod
    def _mentions_data_axis(cls, node: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Constant) and sub.value == "data"
            for sub in ast.walk(node)
        )

    def _module_spans_data_mesh(self) -> bool:
        """True when this module builds a TRAINING mesh with a "data" axis: a
        `Mesh(...)` whose axis names include "data", a `build_mesh(...)` call
        (whose default ParallelismConfig fills "data" with every chip), or a
        `ParallelismConfig(...)` given a data degree — the context in which a
        replicated optimizer-state placement spends data_n x the moment HBM
        each chip needs for the shard of the update it actually computes."""
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            if name == "build_mesh":
                return True
            if name == "ParallelismConfig" and any(
                kw.arg == "data" for kw in node.keywords
            ):
                return True
            if name == "Mesh" and any(
                self._mentions_data_axis(arg)
                for arg in list(node.args) + [kw.value for kw in node.keywords]
            ):
                return True
        return False

    #: Identifier fragments that label a placed tree as optimizer state.
    #: Substring match against every Name/Attribute inside the placed operand.
    _OPT_STATE_LABELS = ("opt_state", "optimizer_state", "adam_state", "moments")

    @classmethod
    def _is_opt_state_expr(cls, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                label = sub.id
            elif isinstance(sub, ast.Attribute):
                label = sub.attr
            else:
                continue
            label = label.lower()
            if any(tok in label for tok in cls._OPT_STATE_LABELS):
                return True
        return False

    @classmethod
    def _placement_is_replicated(cls, node: ast.AST) -> bool:
        """A placement expression that spells REPLICATE explicitly: it contains
        PartitionSpec()/P() calls and every one of them is empty (a
        `NamedSharding(mesh, PartitionSpec())` pytree lands the full tree on
        every chip by construction). Placements without any literal spec —
        derived sharding pytrees, precomputed names — keep the benefit of the
        doubt, same as TPU118."""
        specs = [
            sub
            for sub in ast.walk(node)
            if isinstance(sub, ast.Call)
            and cls._call_name(sub.func) in {"PartitionSpec", "P"}
        ]
        return bool(specs) and all(
            not spec.args and not spec.keywords for spec in specs
        )

    def _check_replicated_optimizer_state(self):
        """TPU120: in a module that builds a data-axis training mesh,
        `device_put` of an optimizer-state tree with no sharding (or a raw
        device, or an explicitly replicated PartitionSpec()) parks fp32 Adam
        moments — 8 bytes/param — on EVERY chip, the single largest avoidable
        HBM account in data-parallel training. The sanctioned spellings derive
        the placement: `derive_opt_state_shardings` (with the planner's
        opt_rules table for ZeRO sharding along "data"), or
        Accelerator.prepare's AcceleratedOptimizer, whose init/out_shardings
        discipline places moments sharded from the first step."""
        if not self.index.imports_jax or not self._module_spans_data_mesh():
            return
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._call_name(node.func) != "device_put":
                continue
            if not node.args or not self._is_opt_state_expr(node.args[0]):
                continue
            placement = None
            if len(node.args) >= 2:
                placement = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg in ("device", "shardings", "sharding"):
                        placement = kw.value
                        break
            missing = placement is None or (
                isinstance(placement, ast.Constant) and placement.value is None
            )
            if (
                missing
                or self._placement_is_devicey(placement)
                or self._placement_is_replicated(placement)
            ):
                self.emit(
                    node,
                    "TPU120",
                    "optimizer state device_put without a sharded placement in a "
                    "data-axis-mesh module replicates fp32 moments (8 bytes/param) "
                    "to every chip — derive the placement with "
                    "derive_opt_state_shardings (pass the planner's opt_rules for "
                    "ZeRO sharding along \"data\"; plan_train_sharding emits it) "
                    "or prepare the optimizer through Accelerator.prepare with "
                    "sharding_rules=\"auto\"",
                )

    # -- host hop in stage handoff (TPU121) ----------------------------------------
    @classmethod
    def _mentions_pipeline_axis(cls, node: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Constant) and sub.value == "pipeline"
            for sub in ast.walk(node)
        )

    def _module_spans_pipeline_mesh(self) -> bool:
        """True when this module builds (or slices) a mesh with a "pipeline"
        axis: a `Mesh(...)`/`build_mesh(...)` naming the axis, a
        `ParallelismConfig(...)` given a pipeline degree, or a
        `slice_mesh(...)` call (the MPMD stage-submesh API itself) — the
        context in which an inter-stage carry lives on one submesh and must
        reach the next as a device-to-device transfer."""
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            if name == "slice_mesh":
                return True
            if name == "ParallelismConfig" and any(
                kw.arg == "pipeline" for kw in node.keywords
            ):
                return True
            if name in ("Mesh", "build_mesh") and any(
                self._mentions_pipeline_axis(arg)
                for arg in list(node.args) + [kw.value for kw in node.keywords]
            ):
                return True
        return False

    #: Identifier fragments that label a value as an inter-stage handoff: the
    #: forward activation carry or the backward cotangent riding between stage
    #: submeshes. Substring match against every Name/Attribute in the operand.
    _HANDOFF_LABELS = (
        "carry", "carries", "activation", "hidden", "handoff",
        "cotangent", "microbatch", "g_out", "g_in", "stage_out", "stage_in",
    )

    @classmethod
    def _is_handoff_expr(cls, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                label = sub.id
            elif isinstance(sub, ast.Attribute):
                label = sub.attr
            else:
                continue
            label = label.lower()
            if any(tok in label for tok in cls._HANDOFF_LABELS):
                return True
        return False

    def _is_numpy_coercion(self, node: ast.Call) -> bool:
        """`np.asarray(...)` / `np.array(...)` through a numpy alias — the
        silent device_get. jnp spellings stay on device and are not flagged."""
        func = node.func
        return (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in (self.index.np_aliases or {"np", "numpy"})
        )

    def _check_host_hop_in_stage_handoff(self):
        """TPU121: in a module that builds a "pipeline" mesh axis, pulling an
        inter-stage activation/gradient carry through the host —
        `jax.device_get(carry)`, `np.asarray(carry)`, or
        `carry.block_until_ready()` between stages — serializes the 1F1B
        schedule on PCIe: every stage stalls behind the transfer instead of
        overlapping via async dispatch. The sanctioned handoff is
        `jax.device_put(carry, NamedSharding(next_stage_mesh, spec))`, a pure
        d2d ICI transfer that an armed TraceGuard leaves unguarded."""
        if not self.index.imports_jax or not self._module_spans_pipeline_mesh():
            return
        msg = (
            "inter-stage carry pulled through the host in a pipeline-mesh "
            "module serializes the 1F1B schedule on PCIe — hand activations "
            "and cotangents to the next stage submesh with jax.device_put("
            "carry, NamedSharding(next_stage_mesh, spec)) (a device-to-device "
            "transfer async dispatch overlaps), and keep TraceGuard armed so "
            "host round-trips fail loudly"
        )
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node.func)
            if name == "device_get" or self._is_numpy_coercion(node):
                if node.args and self._is_handoff_expr(node.args[0]):
                    self.emit(node, "TPU121", msg)
            elif name == "block_until_ready":
                if node.args:
                    operand = node.args[0]
                elif isinstance(node.func, ast.Attribute):
                    operand = node.func.value
                else:
                    continue
                if self._is_handoff_expr(operand):
                    self.emit(node, "TPU121", msg)

    # -- dead partition rules (TPU119) --------------------------------------------
    #: Pattern tokens that name STORAGE details every family table shares, not
    #: module identity — a pattern made only of these can't be judged dead.
    _RULE_GENERIC_TOKENS = {
        "kernel",
        "embedding",
        "embed",
        "bias",
        "scale",
        "layers",
        "layer",
        "params",
        "weight",
    }

    @staticmethod
    def _pattern_tokens(pattern: str) -> List[str]:
        """Identifier-ish fragments of a path regex ("(wq|wk|wv)/kernel" ->
        [wq, wk, wv]), generic storage words removed; single letters are too
        ambiguous to judge."""
        tokens = re.findall(r"[A-Za-z_][A-Za-z0-9_]+", pattern)
        return [
            t
            for t in tokens
            if len(t) >= 2 and t.lower() not in _ModuleChecker._RULE_GENERIC_TOKENS
        ]

    def _sharding_tables(self) -> List[ast.Assign]:
        tables = []
        for node in ast.walk(self.index.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("SHARDING_RULES")
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                tables.append(node)
        return tables

    def _name_evidence(self, exclude: List[ast.AST]) -> Set[str]:
        """Every name-ish string in the module OUTSIDE the rule tables: flax
        submodule names arrive as `name="wq"` constants or f-string parts,
        attribute targets, dict keys, identifiers. This is what a live
        pattern's tokens must connect to."""
        skip = set()
        for table in exclude:
            for sub in ast.walk(table):
                skip.add(id(sub))
        evidence: Set[str] = set()
        for node in ast.walk(self.index.tree):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Name):
                evidence.add(node.id)
            elif isinstance(node, ast.Attribute):
                evidence.add(node.attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                evidence.add(node.name)
            elif isinstance(node, ast.keyword) and node.arg:
                evidence.add(node.arg)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                # Identifier-like strings only (flax `name="wq"` kwargs,
                # f-string parts like "layer_"): free-text constants such as
                # docstrings would vouch for anything they happen to mention.
                if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", node.value):
                    evidence.add(node.value)
        return {e for e in evidence if len(e) >= 2}

    def _check_dead_partition_rule(self):
        """TPU119: a sharding-rules entry whose regex names modules the model
        never defines matches NO parameter path at derivation time — the
        weight it was written to shard silently replicates, the table-side
        twin of TPU118's silent-replication placement. Also flagged: a
        literal string-axis `PartitionSpec(...)` in model code — per-leaf
        placement decisions scattered outside the one rules table the
        derivation seam (and the planner's emitted tables) can audit."""
        if not self.index.imports_jax or not self.index.imports_flax:
            return
        tables = self._sharding_tables()
        evidence = self._name_evidence(exclude=tables) if tables else set()
        for table in tables:
            for entry in table.value.elts:
                if not (isinstance(entry, ast.Tuple) and len(entry.elts) == 2):
                    continue
                pattern = entry.elts[0]
                if not (isinstance(pattern, ast.Constant) and isinstance(pattern.value, str)):
                    continue
                tokens = self._pattern_tokens(pattern.value)
                if not tokens:
                    continue  # all-generic pattern: can't judge statically
                alive = any(tok in ev for tok in tokens for ev in evidence)
                if not alive:
                    self.emit(
                        entry,
                        "TPU119",
                        f"rule pattern {pattern.value!r} names no module this "
                        "model defines — the entry matches no parameter path, "
                        "so the weight it was written to shard silently "
                        "replicates; fix the regex or delete the entry",
                    )
        for node in ast.walk(self.index.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._call_name(node.func) != "PartitionSpec":
                continue
            has_axis_literal = any(
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                for arg in node.args
            ) or any(
                isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                for arg in node.args
                if isinstance(arg, (ast.Tuple, ast.List))
                for sub in arg.elts
            )
            if has_axis_literal:
                self.emit(
                    node,
                    "TPU119",
                    "literal per-leaf PartitionSpec in model code bypasses the "
                    "family's sharding-rules table — move the placement into "
                    "*_SHARDING_RULES (or let sharding_rules=\"auto\" emit it) "
                    "so the one derivation seam sees every decision",
                )

    def _check_jit_placement(self):
        for call in self.index.jit_calls:
            loop = _enclosing_loop(call)
            if loop is not None:
                self.emit(
                    call,
                    "TPU106",
                    "jax.jit inside a loop builds a fresh executable cache every "
                    "iteration — hoist it out of the loop",
                )
            elif _enclosing_function(call) is None:
                self.emit(
                    call,
                    "TPU109",
                    "jax.jit at module scope runs at import time (traces/compiles "
                    "on import); construct it lazily",
                )

    def _check_pjit_annotations(self):
        for call in self.index.jit_calls:
            if not self.index.is_pjit_func(call.func):
                continue
            kwargs = {kw.arg for kw in call.keywords if kw.arg}
            if not kwargs & {"in_shardings", "out_shardings", "in_axis_resources", "out_axis_resources"}:
                self.emit(
                    call,
                    "TPU110",
                    "pjit without in_shardings/out_shardings replicates every "
                    "operand — annotate the partitioning explicitly",
                )

    # -- static_argnums over loop-varying values + donated-buffer reuse ---------
    @staticmethod
    def _literal_argnums(call: ast.Call, kwarg: str) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg != kwarg:
                continue
            try:
                value = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return None
            if isinstance(value, int):
                return (value,)
            if isinstance(value, (tuple, list)) and all(isinstance(v, int) for v in value):
                return tuple(value)
        return None

    @staticmethod
    def _owned_by(node: ast.AST, scope: ast.AST) -> bool:
        """Does `node` belong to `scope`'s own frame (not a nested function's)?"""
        owner = _enclosing_function(node)
        return owner is scope or (owner is None and isinstance(scope, ast.Module))

    def _jitted_bindings(self, scope: ast.AST) -> Dict[str, ast.Call]:
        """`f = jax.jit(g, ...)` assignments directly inside `scope`'s frame."""
        out: Dict[str, ast.Call] = {}
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Assign)
                and self._owned_by(node, scope)
                and isinstance(node.value, ast.Call)
                and node.value in self.index.jit_calls
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                out[node.targets[0].id] = node.value
        return out

    def _scopes(self):
        yield self.index.tree
        for node in ast.walk(self.index.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _check_static_argnums_and_donation(self):
        for scope in self._scopes():
            bindings = self._jitted_bindings(scope)
            if not bindings:
                continue
            static = {
                name: nums
                for name, call in bindings.items()
                if (nums := self._literal_argnums(call, "static_argnums")) is not None
            }
            donated = {
                name: nums
                for name, call in bindings.items()
                if (nums := self._literal_argnums(call, "donate_argnums")) is not None
            }
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                    continue
                if not self._owned_by(node, scope):
                    continue
                name = node.func.id
                if name in static:
                    loop = _enclosing_loop(node)
                    if loop is not None:
                        loop_vars = {
                            n.id
                            for n in ast.walk(loop.target)
                            if isinstance(n, ast.Name)
                        } if isinstance(loop, ast.For) else set()
                        for pos in static[name]:
                            if pos < len(node.args) and any(
                                isinstance(n, ast.Name) and n.id in loop_vars
                                for n in ast.walk(node.args[pos])
                            ):
                                self.emit(
                                    node,
                                    "TPU107",
                                    f"static_argnums position {pos} of `{name}` is fed "
                                    "the loop variable — every iteration recompiles",
                                )
                if name in donated:
                    self._check_donated_reuse(scope, node, donated[name])

    def _check_donated_reuse(self, scope: ast.AST, call: ast.Call, positions: Sequence[int]):
        donated_names = {
            call.args[p].id
            for p in positions
            if p < len(call.args) and isinstance(call.args[p], ast.Name)
        }
        if not donated_names:
            return
        call_line = call.lineno
        rebound: Set[str] = set()
        in_call = {id(n) for n in ast.walk(call)}  # the donation site itself
        for node in sorted(
            (
                n
                for n in ast.walk(scope)
                if hasattr(n, "lineno") and n.lineno >= call_line and id(n) not in in_call
                # Same frame only: a nested function's own `params` is a fresh
                # binding, not the donated buffer (and must neither be flagged
                # nor mask a real reuse as a rebind).
                and self._owned_by(n, scope)
            ),
            key=lambda n: (n.lineno, n.col_offset),
        ):
            if isinstance(node, ast.Name) and node.id in donated_names:
                parent = getattr(node, "_tpu_parent", None)
                if (
                    isinstance(parent, ast.Attribute)
                    and parent.value is node
                    and parent.attr in STATIC_ATTRS
                ):
                    continue  # .shape/.dtype metadata stays valid after donation
                if isinstance(node.ctx, ast.Store):
                    rebound.add(node.id)
                elif isinstance(node.ctx, ast.Load) and node.id not in rebound:
                    self.emit(
                        node,
                        "TPU108",
                        f"`{node.id}` was donated to the jitted call on line "
                        f"{call_line}; its buffer is invalidated — rebind it to "
                        "the call's output",
                    )
                    rebound.add(node.id)  # one finding per name is enough

    # -- closure scalar capture -------------------------------------------------
    def _check_closure_capture(self):
        for root in self.index.jit_roots:
            enclosing = _enclosing_function(root)
            if enclosing is None:
                continue
            scalar_locals: Set[str] = set()
            for node in ast.walk(enclosing):
                if _enclosing_function(node) is not enclosing:
                    continue
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
                    if isinstance(node.value.value, (int, float)) and not isinstance(
                        node.value.value, bool
                    ):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                scalar_locals.add(tgt.id)
                elif (
                    isinstance(node, ast.AugAssign)
                    and isinstance(node.target, ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, (int, float))
                ):
                    # `i += 1`-style counters are Python scalars; `acc += x`
                    # may well be a traced array accumulator — don't flag it.
                    scalar_locals.add(node.target.id)
            if not scalar_locals:
                continue
            local = _param_names(root)
            for node in ast.walk(root):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in scalar_locals
                    and node.id not in local
                ):
                    self.emit(
                        node,
                        "TPU105",
                        f"`{node.id}` is a Python scalar captured from the enclosing "
                        "scope — it is baked in at trace time; pass it as an operand",
                    )
                    scalar_locals.discard(node.id)  # once per name per root


def analyze_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one Python source. Returns findings with suppressions applied.
    Unparseable sources return no findings (a syntax error is the Python
    toolchain's job, not this linter's) — they still count as scanned."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []  # unparseable files are skipped (not this linter's concern)

    index = _ModuleIndex(tree)
    findings: List[Finding] = []

    seen: Set[int] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        checker = _FunctionChecker(index, fn, path)
        if fn in index.reachable:
            checker.check_traced_rules()
        else:
            checker.check_host_loop_syncs()
            checker.check_span_hazards()
        findings.extend(checker.findings)

    findings.extend(_ModuleChecker(index, path).run())

    per_line, file_wide = _parse_suppressions(source)
    kept: List[Finding] = []
    for f in findings:
        if "all" in file_wide or f.rule_id in file_wide:
            continue
        line_rules = per_line.get(f.line, set())
        if "all" in line_rules or f.rule_id in line_rules:
            continue
        kept.append(f)
    return kept

"""T5 family encoder-decoder in flax — the reference's T0pp-11B config
(benchmarks/README.md:35: T0pp fp32, 0.05 s/token on 2x Titan RTX). The only
encoder-decoder in the benchmark table; brings cross-attention and relative
position biases into the model zoo.

T5 v1.1 architecture (T0pp's base): RMSNorm (no bias, pre-LN), relative position
bias on the FIRST layer of each stack shared with the rest, gated-gelu FFN
(wi_0/wi_1), NO absolute position embeddings, un-tied lm_head, and the decoder
input scaled... not at all — T5 famously multiplies nothing; logits are scaled by
d_model**-0.5 ONLY when the head is tied (v1.0); v1.1 unties, so no scale."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import dot_product_attention, update_decode_cache
from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat

T5_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"(wi_0|wi_1)/kernel", (None, "model")),
    (r"wo_ff/kernel", ("model", None)),
    (r"shared/embedding", ("model", None)),
    (r"lm_head/kernel", (None, "model")),
]


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096
    d_kv: int = 64
    d_ff: int = 10240
    num_layers: int = 24          # encoder layers
    num_decoder_layers: int = 24
    num_heads: int = 64
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    max_position_embeddings: int = 1024  # practical bound for cache sizing; T5 has no absolute positions
    decode_cache_length: int = 0
    param_dtype: str = "float32"
    # v1.0 (t5-small/base/large, reference loads them via load_checkpoint_in_model
    # utils/modeling.py:1565): head tied to the shared embedding with a
    # d_model**-0.5 logit scale, single relu `wi` FFN. v1.1 (default here:
    # t5-v1_1-*, T0pp, flan-t5) unties the head and gates the FFN.
    tie_word_embeddings: bool = False
    feed_forward_proj: str = "gated-gelu"  # gated-gelu | relu

    def __post_init__(self):
        if self.feed_forward_proj not in ("gated-gelu", "relu"):
            # 'gated-relu' etc. exist in HF configs; silently building the
            # gated-GELU FFN for them would produce wrong logits with no error.
            raise ValueError(
                f"feed_forward_proj must be 'gated-gelu' (v1.1) or 'relu' (v1.0), "
                f"got {self.feed_forward_proj!r}"
            )

    @property
    def _pdtype(self):
        return jnp.dtype(self.param_dtype)


class T5RMSNorm(nn.Module):
    eps: float = 1e-6
    param_dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.dtype(self.param_dtype))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


def relative_position_bucket(relative_position, bidirectional: bool, num_buckets: int, max_distance: int):
    """T5's log-bucketed relative positions (HF modeling_t5._relative_position_bucket)."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5RelativeBias(nn.Module):
    """The learned relative-position bias table; lives on the FIRST block of each
    stack and is passed to the rest (T5's weight-sharing scheme)."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_positions, k_positions):
        cfg = self.config
        table = self.param(
            "rel_embedding",
            nn.initializers.normal(1.0),
            (cfg.relative_attention_num_buckets, cfg.num_heads),
            cfg._pdtype,
        )
        rel = k_positions[None, :] - q_positions[:, None]  # [q, k]
        buckets = relative_position_bucket(
            rel, self.bidirectional, cfg.relative_attention_num_buckets, cfg.relative_attention_max_distance
        )
        bias = table[buckets]  # [q, k, heads]
        return jnp.transpose(bias, (2, 0, 1))[None]  # [1, heads, q, k]


class T5Attention(nn.Module):
    config: T5Config
    causal: bool = False
    use_cache: bool = False

    @nn.compact
    def __call__(self, hidden, kv_hidden=None, bias=None, mask=None):
        cfg = self.config
        b, s, _ = hidden.shape
        h, d = cfg.num_heads, cfg.d_kv
        kv_src = hidden if kv_hidden is None else kv_hidden
        q = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wq")(hidden).reshape(b, s, h, d)

        def project_kv(src):
            k = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wk")(src)
            v = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wv")(src)
            return k.reshape(b, src.shape[1], h, d), v.reshape(b, src.shape[1], h, d)

        # T5 does NOT scale attention scores by 1/sqrt(d): pass scale=1.0.
        if self.use_cache and kv_hidden is not None:
            # Cross-attention K/V depend only on the encoder output: project ONCE
            # (the prime call) into the cache, then every decode-loop step reads
            # them back instead of re-running two Dense ops over the full encoder
            # sequence per token. has_variable is trace-static: the prime program
            # computes+stores, the step program only reads.
            if self.has_variable("cache", "cross_key"):
                k = self.variable("cache", "cross_key", None).value
                v = self.variable("cache", "cross_value", None).value
            else:
                k, v = project_kv(kv_src)
                self.variable("cache", "cross_key", lambda: k)
                self.variable("cache", "cross_value", lambda: v)
            out = dot_product_attention(q, k, v, mask=mask, bias=bias, causal=False, scale=1.0)
        elif self.use_cache and kv_hidden is None and cfg.decode_cache_length:
            k, v = project_kv(kv_src)
            k_all, v_all, decode_mask = update_decode_cache(self, k, v, cfg.decode_cache_length)
            out = dot_product_attention(
                q, k_all, v_all, mask=decode_mask, bias=bias, causal=False, scale=1.0
            )
        else:
            k, v = project_kv(kv_src)
            out = dot_product_attention(
                q, k, v, mask=mask, bias=bias, causal=self.causal and kv_hidden is None, scale=1.0
            )
        return nn.Dense(cfg.d_model, use_bias=False, param_dtype=cfg._pdtype, name="wo")(
            out.reshape(b, s, h * d)
        )


class T5FF(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        if cfg.feed_forward_proj == "relu":
            # v1.0 FFN: single projection + relu (HF T5DenseActDense).
            mid = nn.relu(
                nn.Dense(cfg.d_ff, use_bias=False, param_dtype=cfg._pdtype, name="wi")(hidden)
            )
        else:
            gate = nn.gelu(
                nn.Dense(cfg.d_ff, use_bias=False, param_dtype=cfg._pdtype, name="wi_0")(hidden),
                approximate=True,
            )
            up = nn.Dense(cfg.d_ff, use_bias=False, param_dtype=cfg._pdtype, name="wi_1")(hidden)
            mid = gate * up
        return nn.Dense(cfg.d_model, use_bias=False, param_dtype=cfg._pdtype, name="wo_ff")(mid)


class T5EncoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, hidden, bias, mask):
        cfg = self.config
        attn = T5Attention(cfg, causal=False, name="attention")(
            T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype, name="input_norm")(hidden), bias=bias, mask=mask
        )
        hidden = constrain_activation(hidden + attn)
        ff = T5FF(cfg, name="ff")(T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype, name="ff_norm")(hidden))
        return constrain_activation(hidden + ff)


class T5DecoderBlock(nn.Module):
    config: T5Config
    use_cache: bool = False

    @nn.compact
    def __call__(self, hidden, encoder_hidden, self_bias, enc_mask):
        cfg = self.config
        attn = T5Attention(cfg, causal=True, use_cache=self.use_cache, name="self_attention")(
            T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype, name="input_norm")(hidden), bias=self_bias
        )
        hidden = constrain_activation(hidden + attn)
        cross = T5Attention(cfg, use_cache=self.use_cache, name="cross_attention")(
            T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype, name="cross_norm")(hidden),
            kv_hidden=encoder_hidden,
            mask=enc_mask,
        )
        hidden = constrain_activation(hidden + cross)
        ff = T5FF(cfg, name="ff")(T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype, name="ff_norm")(hidden))
        return constrain_activation(hidden + ff)


class T5ForConditionalGeneration(nn.Module):
    """Encoder-decoder forward. Two entry modes:
      - `__call__(input_ids, decoder_input_ids)`: full teacher-forced forward.
      - `encode(input_ids)` / `decode(decoder_input_ids, encoder_hidden, positions)`:
        the split used by cached generation (encode once, decode incrementally)."""

    config: T5Config
    use_cache: bool = False

    def setup(self):
        # setup() forbids explicit name=; attributes name the params. Lists get
        # auto-suffixed names ("enc_blocks_0", ...) — the HF mapping uses them.
        cfg = self.config
        self.shared = nn.Embed(cfg.vocab_size, cfg.d_model, param_dtype=cfg._pdtype)
        self.enc_bias = T5RelativeBias(cfg, bidirectional=True)
        self.dec_bias = T5RelativeBias(cfg, bidirectional=False)
        self.enc_blocks = [maybe_remat(T5EncoderBlock)(cfg) for _ in range(cfg.num_layers)]
        self.dec_blocks = [
            maybe_remat(T5DecoderBlock)(cfg, use_cache=self.use_cache)
            for _ in range(cfg.num_decoder_layers)
        ]
        self.enc_final_norm = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype)
        self.dec_final_norm = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False, param_dtype=cfg._pdtype)

    def _head(self, hidden):
        """v1.1: separate lm_head. v1.0: tied to the shared embedding with the
        d_model**-0.5 rescale HF applies before the tied projection."""
        if self.config.tie_word_embeddings:
            return self.shared.attend(hidden * (self.config.d_model ** -0.5))
        return self.lm_head(hidden)

    def encode(self, input_ids, attention_mask=None):
        s = input_ids.shape[1]
        pos = jnp.arange(s)
        bias = self.enc_bias(pos, pos)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        hidden = self.shared(input_ids)
        for block in self.enc_blocks:
            hidden = block(hidden, bias, mask)
        return self.enc_final_norm(hidden)

    def decode(self, decoder_input_ids, encoder_hidden, positions=None, enc_mask=None):
        cfg = self.config
        b, s = decoder_input_ids.shape
        if positions is None:
            q_pos = jnp.arange(s)
        else:
            # Incremental decoding: absolute positions of the current tokens.
            q_pos = positions
        if self.use_cache and cfg.decode_cache_length:
            k_pos = jnp.arange(cfg.decode_cache_length)
        else:
            k_pos = jnp.arange(s) if positions is None else positions
        bias = self.dec_bias(q_pos, k_pos)
        hidden = self.shared(decoder_input_ids)
        for block in self.dec_blocks:
            hidden = block(hidden, encoder_hidden, bias, enc_mask)
        hidden = self.dec_final_norm(hidden)
        return self._head(hidden)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None):
        encoder_hidden = self.encode(input_ids, attention_mask)
        enc_mask = None
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        return self.decode(decoder_input_ids, encoder_hidden, enc_mask=enc_mask)


def seq2seq_lm_loss(params, batch, apply_fn):
    """Teacher-forced cross-entropy on decoder targets; labels < 0 are ignored."""
    logits = apply_fn(params, batch["input_ids"], batch["decoder_input_ids"], batch.get("attention_mask"))
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def create_t5_model(
    config: Optional[T5Config] = None, rng=None, seq_len: int = 512, param_dtype=None
) -> Model:
    import dataclasses

    config = config or t5_tiny()
    if param_dtype is not None:
        config = dataclasses.replace(config, param_dtype=str(jnp.dtype(param_dtype)))
    if rng is None:
        rng = jax.random.key(0)
    module = T5ForConditionalGeneration(config)
    s = min(seq_len, config.max_position_embeddings)
    sample = jnp.zeros((1, s), dtype=jnp.int32)
    params = jax.jit(module.init)(rng, sample, sample[:, : max(s // 2, 1)])
    return Model.from_flax(module, params, loss_fn=seq2seq_lm_loss, sharding_rules=T5_SHARDING_RULES)


def _reject_tied_head(config: T5Config, what: str):
    """The layered/pipeline splits put `lm_head` in the tail stage; a v1.0
    tied head lives inside the shared embedding (prelude), so the tail would
    need the embedding replicated — keep the restriction explicit instead of
    silently doubling the largest tensor."""
    if config.tie_word_embeddings:
        raise NotImplementedError(
            f"{what} does not support tie_word_embeddings=True (T5 v1.0): the "
            "tied head would replicate the shared embedding into the tail "
            "stage. Use the resident model path, or a v1.1 checkpoint."
        )


class T5LayeredApply:
    """LayeredApply protocol for tier-streamed encoder-decoder execution — the
    route by which the reference's T0pp-11B fp32 device_map row runs inside
    bounded HBM. The layer list is the encoder stack followed by the decoder
    stack; entries are structure-keyed ({"enc": ...} vs {"dec": ...}) so the
    streaming loop's jit compiles one executable per block kind, and the first
    decoder entry additionally carries `enc_final_norm` (applied to the encoder
    output exactly once, before any cross-attention reads it)."""

    def __init__(self, config: T5Config):
        _reject_tied_head(config, "T5LayeredApply (tier-streamed execution)")
        self.config = config

    def split(self, params):
        cfg = self.config
        inner = params["params"]
        prelude = {"params": {k: inner[k] for k in ("shared", "enc_bias", "dec_bias")}}
        layers = []
        for i in range(cfg.num_layers):
            layers.append({"params": {"enc": inner[f"enc_blocks_{i}"]}})
        for i in range(cfg.num_decoder_layers):
            entry = {"params": {"dec": inner[f"dec_blocks_{i}"]}}
            if i == 0:
                entry["params"]["enc_final_norm"] = inner["enc_final_norm"]
            layers.append(entry)
        tail = {"params": {k: inner[k] for k in ("dec_final_norm", "lm_head")}}
        return prelude, layers, tail

    def join(self, prelude, layers, tail):
        cfg = self.config
        inner = dict(prelude["params"])
        for i in range(cfg.num_layers):
            inner[f"enc_blocks_{i}"] = layers[i]["params"]["enc"]
        for i in range(cfg.num_decoder_layers):
            entry = layers[cfg.num_layers + i]["params"]
            inner[f"dec_blocks_{i}"] = entry["dec"]
            if "enc_final_norm" in entry:
                inner["enc_final_norm"] = entry["enc_final_norm"]
        inner.update(tail["params"])
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, decoder_input_ids, attention_mask=None):
        cfg = self.config
        inner = prelude_params["params"]
        embed = nn.Embed(cfg.vocab_size, cfg.d_model)
        enc = embed.apply({"params": {"embedding": inner["shared"]["embedding"]}}, input_ids)
        dec = embed.apply({"params": {"embedding": inner["shared"]["embedding"]}}, decoder_input_ids)
        enc_pos = jnp.arange(input_ids.shape[1])
        dec_pos = jnp.arange(decoder_input_ids.shape[1])
        enc_bias = T5RelativeBias(cfg, bidirectional=True).apply(
            {"params": inner["enc_bias"]}, enc_pos, enc_pos
        )
        dec_bias = T5RelativeBias(cfg, bidirectional=False).apply(
            {"params": inner["dec_bias"]}, dec_pos, dec_pos
        )
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        else:
            # The carry must have a stable pytree structure across layer calls, so
            # "no mask" is an all-ones mask rather than None.
            enc_mask = jnp.ones((input_ids.shape[0], 1, 1, input_ids.shape[1]), bool)
        return {"enc": enc, "dec": dec, "enc_bias": enc_bias, "dec_bias": dec_bias, "enc_mask": enc_mask}

    def apply_layer(self, layer_params, carry):
        cfg = self.config
        inner = layer_params["params"]
        carry = dict(carry)
        if "enc" in inner:
            carry["enc"] = T5EncoderBlock(cfg).apply(
                {"params": inner["enc"]}, carry["enc"], carry["enc_bias"], carry["enc_mask"]
            )
            return carry
        if "enc_final_norm" in inner:
            carry["enc"] = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype).apply(
                {"params": inner["enc_final_norm"]}, carry["enc"]
            )
        carry["dec"] = T5DecoderBlock(cfg).apply(
            {"params": inner["dec"]}, carry["dec"], carry["enc"], carry["dec_bias"], carry["enc_mask"]
        )
        return carry

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        inner = tail_params["params"]
        hidden = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype).apply(
            {"params": inner["dec_final_norm"]}, carry["dec"]
        )
        return nn.Dense(cfg.vocab_size, use_bias=False).apply({"params": inner["lm_head"]}, hidden)


class T5PipelineApply:
    """Encoder-decoder pipeline decomposition (consumed by
    `parallel.pipeline.PipelinedModel`'s two-phase ring schedule — the in-tree
    replacement for Megatron's T5 pipeline, reference utils/megatron_lm.py:702
    `T5TrainStep` + :1004-1010 schedule selection).

    Each pipeline stage holds a chunk of BOTH stacks; a microbatch rides the
    stage ring twice — encoder chunks on the first pass, then `apply_promote`
    (the encoder final norm, applied exactly once before any cross-attention)
    at stage 0, then decoder chunks on the second pass. The carry holds both
    streams ({"enc", "dec", biases, mask}), so its pytree structure is uniform
    across every hop."""

    def __init__(self, config: T5Config):
        _reject_tied_head(config, "T5PipelineApply (pipeline parallelism)")
        self.config = config

    def split(self, params):
        cfg = self.config
        inner = params["params"]
        prelude = {
            "params": {k: inner[k] for k in ("shared", "enc_bias", "dec_bias", "enc_final_norm")}
        }
        enc_layers = [{"params": inner[f"enc_blocks_{i}"]} for i in range(cfg.num_layers)]
        dec_layers = [{"params": inner[f"dec_blocks_{i}"]} for i in range(cfg.num_decoder_layers)]
        tail = {"params": {k: inner[k] for k in ("dec_final_norm", "lm_head")}}
        return prelude, enc_layers, dec_layers, tail

    def join(self, prelude, enc_layers, dec_layers, tail):
        inner = dict(prelude["params"])
        for i, lp in enumerate(enc_layers):
            inner[f"enc_blocks_{i}"] = lp["params"]
        for i, lp in enumerate(dec_layers):
            inner[f"dec_blocks_{i}"] = lp["params"]
        inner.update(tail["params"])
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, decoder_input_ids, attention_mask=None):
        """Per-MICROBATCH carry only ({"enc","dec","enc_mask"}): the relative-
        position biases are input-independent and come from `apply_static_carry`
        — computed once per stage from the replicated prelude instead of riding
        the ppermute ring on every hop."""
        cfg = self.config
        inner = prelude_params["params"]
        embed = nn.Embed(cfg.vocab_size, cfg.d_model)
        enc = embed.apply({"params": {"embedding": inner["shared"]["embedding"]}}, input_ids)
        dec = embed.apply({"params": {"embedding": inner["shared"]["embedding"]}}, decoder_input_ids)
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        else:
            # Stable carry structure: "no mask" is all-ones, not None.
            enc_mask = jnp.ones((input_ids.shape[0], 1, 1, input_ids.shape[1]), bool)
        return {"enc": enc, "dec": dec, "enc_mask": enc_mask}

    def apply_static_carry(self, prelude_params, input_ids, decoder_input_ids, attention_mask=None):
        """Input-independent carry entries (the relative-position bias tables over
        the static sequence lengths). Every stage holds the replicated prelude, so
        each computes these locally — they never rotate over ICI."""
        cfg = self.config
        inner = prelude_params["params"]
        enc_pos = jnp.arange(input_ids.shape[1])
        dec_pos = jnp.arange(decoder_input_ids.shape[1])
        enc_bias = T5RelativeBias(cfg, bidirectional=True).apply(
            {"params": inner["enc_bias"]}, enc_pos, enc_pos
        )
        dec_bias = T5RelativeBias(cfg, bidirectional=False).apply(
            {"params": inner["dec_bias"]}, dec_pos, dec_pos
        )
        return {"enc_bias": enc_bias, "dec_bias": dec_bias}

    def apply_enc_layer(self, layer_params, carry):
        cfg = self.config
        carry = dict(carry)
        carry["enc"] = T5EncoderBlock(cfg).apply(
            {"params": layer_params["params"]}, carry["enc"], carry["enc_bias"], carry["enc_mask"]
        )
        return carry

    def apply_promote(self, prelude_params, carry):
        """Encoder -> decoder phase handoff: the final encoder norm, exactly once."""
        cfg = self.config
        carry = dict(carry)
        carry["enc"] = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype).apply(
            {"params": prelude_params["params"]["enc_final_norm"]}, carry["enc"]
        )
        return carry

    def apply_dec_layer(self, layer_params, carry):
        cfg = self.config
        carry = dict(carry)
        carry["dec"] = T5DecoderBlock(cfg).apply(
            {"params": layer_params["params"]},
            carry["dec"],
            carry["enc"],
            carry["dec_bias"],
            carry["enc_mask"],
        )
        return carry

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        inner = tail_params["params"]
        hidden = T5RMSNorm(cfg.layer_norm_eps, cfg.param_dtype).apply(
            {"params": inner["dec_final_norm"]}, carry["dec"]
        )
        return nn.Dense(cfg.vocab_size, use_bias=False).apply({"params": inner["lm_head"]}, hidden)


def t0pp_11b() -> T5Config:
    """bigscience/T0pp dims (T5 v1.1 xxl; reference benchmarks/README.md:35)."""
    return T5Config()


def t5_tiny() -> T5Config:
    return T5Config(
        vocab_size=512,
        d_model=64,
        d_kv=16,
        d_ff=128,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        max_position_embeddings=128,
    )


def t5_small_v1_0() -> T5Config:
    """google-t5/t5-small dims — the v1.0 layout (tied head, relu FFN) the
    reference loads through load_checkpoint_in_model (utils/modeling.py:1565)."""
    return T5Config(
        d_model=512,
        d_kv=64,
        d_ff=2048,
        num_layers=6,
        num_decoder_layers=6,
        num_heads=8,
        tie_word_embeddings=True,
        feed_forward_proj="relu",
    )


def t5_tiny_v1_0() -> T5Config:
    import dataclasses

    return dataclasses.replace(t5_tiny(), tie_word_embeddings=True, feed_forward_proj="relu")

"""BERT-family encoder in flax — the `examples/nlp_example.py` model
(reference trains HF `bert-base-cased` on GLUE/MRPC; BASELINE.md GLUE-BERT metric).

Fresh flax implementation (not a port): pre-computed additive masks, fused QKV
projection (one matmul feeding the MXU instead of three), fp32 layernorms under bf16
compute, and Megatron-style TP sharding rules shipped as path regexes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import dot_product_attention

from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat

# Megatron-layout TP rules: fused qkv/mlp-up column-parallel, out/mlp-down row-parallel,
# vocab embedding sharded on the vocab dim. Consumed by parallel/sharding.py.
BERT_SHARDING_RULES = [
    (r"qkv/kernel", (None, "model")),
    (r"attn_out/kernel", ("model", None)),
    (r"mlp_up/kernel", (None, "model")),
    (r"mlp_down/kernel", ("model", None)),
    (r"word_embeddings/embedding", ("model", None)),
]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    dtype: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        h, d = cfg.num_attention_heads, cfg.head_dim
        qkv = nn.Dense(3 * cfg.hidden_size, name="qkv")(hidden)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s, _ = hidden.shape
        q = q.reshape(b, s, h, d)
        k = k.reshape(b, s, h, d)
        v = v.reshape(b, s, h, d)
        out = dot_product_attention(q, k, v, mask=mask)
        out = out.reshape(b, s, cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, name="attn_out")(out)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        attn = BertSelfAttention(cfg, name="attention")(hidden, mask)
        hidden = constrain_activation(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32, name="attn_ln")(hidden + attn)
        )
        up = nn.Dense(cfg.intermediate_size, name="mlp_up")(hidden)
        up = nn.gelu(up, approximate=True)
        down = nn.Dense(cfg.hidden_size, name="mlp_down")(up)
        return constrain_activation(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32, name="mlp_ln")(hidden + down)
        )


class BertEncoder(nn.Module):
    """Embeddings + transformer stack; returns (sequence_output, pooled_output)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.config
        b, s = input_ids.shape
        words = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="word_embeddings")(input_ids)
        positions = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, name="position_embeddings")(
            jnp.arange(s)[None, :]
        )
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        types = nn.Embed(cfg.type_vocab_size, cfg.hidden_size, name="token_type_embeddings")(token_type_ids)
        hidden = constrain_activation(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32, name="embeddings_ln")(
                words + positions + types
            )
        )
        Layer = maybe_remat(BertLayer)
        for i in range(cfg.num_hidden_layers):
            hidden = Layer(cfg, name=f"layer_{i}")(hidden, attention_mask)
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, name="pooler")(hidden[:, 0]))
        return hidden, pooled


class BertForSequenceClassification(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        _, pooled = BertEncoder(self.config, name="bert")(input_ids, attention_mask, token_type_ids)
        return nn.Dense(self.config.num_labels, name="classifier")(pooled)


def sequence_classification_loss(params, batch, apply_fn):
    """Mean softmax cross-entropy over the global batch; the per-device mean over a
    ("data","fsdp")-sharded batch is what makes the gradient psum implicit."""
    logits = apply_fn(
        params,
        batch["input_ids"],
        batch.get("attention_mask"),
        batch.get("token_type_ids"),
    )
    labels = batch["labels"]
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return nll.mean()


def create_bert_model(config: Optional[BertConfig] = None, rng=None, seq_len: int = 128) -> Model:
    """Initialized Model bundle for sequence classification."""
    config = config or BertConfig()
    if rng is None:
        rng = jax.random.key(0)
    module = BertForSequenceClassification(config)
    sample = jnp.zeros((1, seq_len), dtype=jnp.int32)
    params = module.init(rng, sample)
    return Model.from_flax(
        module, params, loss_fn=sequence_classification_loss, sharding_rules=BERT_SHARDING_RULES
    )


def bert_base(num_labels: int = 2) -> BertConfig:
    return BertConfig(num_labels=num_labels)


def bert_tiny(num_labels: int = 2) -> BertConfig:
    """4-layer test-size config."""
    return BertConfig(
        vocab_size=1024,
        hidden_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        intermediate_size=512,
        max_position_embeddings=128,
        num_labels=num_labels,
    )

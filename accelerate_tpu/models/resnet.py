"""ResNet family (v1.5 bottleneck) in flax — the cv_example/data-parallel benchmark
model (BASELINE.json configs: "examples/cv_example.py — ResNet-50 image
classification"). NHWC layout (TPU-native conv layout), BatchNorm with mutable
batch_stats threaded through the Model bundle's apply.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..modeling import Model


@dataclass
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    num_channels: int = 3


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool):
        norm = lambda name: nn.BatchNorm(use_running_average=not train, momentum=0.9, name=name)
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        y = nn.Conv(self.features, (3, 3), self.strides, use_bias=False, name="conv2")(y)
        y = nn.relu(norm("bn2")(y))
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, name="conv3")(y)
        y = norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features * 4, (1, 1), self.strides, use_bias=False, name="downsample_conv"
            )(residual)
            residual = norm("downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):  # x: [B, H, W, C] (NHWC)
        cfg = self.config
        x = nn.Conv(cfg.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False, name="stem_conv")(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, size in enumerate(cfg.stage_sizes):
            for j in range(size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(cfg.width * 2**i, strides, name=f"stage{i}_block{j}")(x, train)
        x = x.mean(axis=(1, 2))
        return nn.Dense(cfg.num_classes, name="classifier")(x)


def image_classification_loss(variables, batch, apply_fn):
    """Cross-entropy over `pixel_values`/`labels`. BatchNorm runs on (stop-gradiented)
    running stats inside the differentiated loss so the optimizer never touches
    `batch_stats` — zero-grad under adam means those leaves stay fixed."""
    if isinstance(variables, dict) and "batch_stats" in variables:
        variables = {
            **variables,
            "batch_stats": jax.tree_util.tree_map(jax.lax.stop_gradient, variables["batch_stats"]),
        }
    logits = apply_fn(variables, batch["pixel_values"])
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    return nll.mean()


def create_resnet_model(config: Optional[ResNetConfig] = None, rng=None, image_size: int = 224) -> Model:
    config = config or ResNetConfig()
    if rng is None:
        rng = jax.random.key(0)
    module = ResNet(config)
    sample = jnp.zeros((1, image_size, image_size, config.num_channels), jnp.float32)
    variables = module.init(rng, sample)
    return Model.from_flax(module, variables, loss_fn=image_classification_loss)


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=num_classes)


def resnet18_ish(num_classes: int = 1000) -> ResNetConfig:
    """Shallow bottleneck variant for quicker runs."""
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), num_classes=num_classes)


def resnet_tiny(num_classes: int = 4) -> ResNetConfig:
    """Test-size config."""
    return ResNetConfig(stage_sizes=(1, 1), num_classes=num_classes, width=8)

"""In-tree model families (flax), each shipping TP sharding rules and a loss.

These cover the reference's benchmark configs (BASELINE.json): BERT (GLUE),
Llama (FSDP fine-tune + big-model inference), ResNet (cv_example)."""

from .bert import BertConfig, BertForSequenceClassification, bert_base, bert_tiny, create_bert_model
from .llama import (
    LlamaConfig,
    LlamaForCausalLM,
    create_llama_model,
    llama3_8b,
    llama_1b,
    llama_tiny,
)
from .resnet import ResNet, ResNetConfig, create_resnet_model, resnet50, resnet_tiny
from .mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    create_mixtral_model,
    mixtral_8x7b,
    mixtral_tiny,
)
from .gptj import GPTJConfig, GPTJForCausalLM, create_gptj_model, gptj_6b, gptj_tiny
from .gpt_neox import (
    GPTNeoXConfig,
    GPTNeoXForCausalLM,
    create_gpt_neox_model,
    gpt_neox_20b,
    gpt_neox_tiny,
)
from .opt import OPTConfig, OPTForCausalLM, create_opt_model, opt_30b, opt_tiny
from .t5 import (
    T5Config,
    T5ForConditionalGeneration,
    create_t5_model,
    t0pp_11b,
    t5_small_v1_0,
    t5_tiny,
    t5_tiny_v1_0,
)

# The single source of truth for named in-tree models: name -> (interchange
# family, dataclass-config factory). The estimate registry and the convert CLI
# both derive from this, so a new model registers exactly once.
MODEL_REGISTRY = {
    "bert-base": ("bert", bert_base),
    "bert-tiny": ("bert", bert_tiny),
    "llama-3-8b": ("llama", llama3_8b),
    "llama-1b": ("llama", llama_1b),
    "llama-tiny": ("llama", llama_tiny),
    "mixtral-8x7b": ("mixtral", mixtral_8x7b),
    "mixtral-tiny": ("mixtral", mixtral_tiny),
    "gptj-6b": ("gptj", gptj_6b),
    "gptj-tiny": ("gptj", gptj_tiny),
    "gpt-neox-20b": ("gpt_neox", gpt_neox_20b),
    "gpt-neox-tiny": ("gpt_neox", gpt_neox_tiny),
    "opt-30b": ("opt", opt_30b),
    "opt-tiny": ("opt", opt_tiny),
    "t0pp-11b": ("t5", t0pp_11b),
    "t5-tiny": ("t5", t5_tiny),
    "t5-small": ("t5", t5_small_v1_0),
    "t5-tiny-v1-0": ("t5", t5_tiny_v1_0),
}

# family -> Model-bundle creator (the `create_*` entry points above).
CREATE_BY_FAMILY = {
    "bert": create_bert_model,
    "llama": create_llama_model,
    "mixtral": create_mixtral_model,
    "gptj": create_gptj_model,
    "gpt_neox": create_gpt_neox_model,
    "opt": create_opt_model,
    "t5": create_t5_model,
}

# family -> (flax module class name, LayeredApply class) for models shipping a
# prelude/layers/tail decomposition. Consumed by `layered_for_model`, the seam
# `Accelerator.prepare(sharding_rules="auto")` on a "pipeline" mesh and the
# `plan --mesh ... pipeline=` CLI use to get the per-layer param split that
# `plan_pipeline_stages` balances and `parallel/mpmd.py` executes. T5 is absent
# on purpose: its encoder/decoder split rides the pipeline (promote) protocol,
# not the linear-carry LayeredApply contract the MPMD runtime assumes.
LAYERED_BY_FAMILY = {
    "llama": "LlamaForCausalLM",
    "gpt_neox": "GPTNeoXForCausalLM",
    "gptj": "GPTJForCausalLM",
    "opt": "OPTForCausalLM",
}


def _layered_classes():
    from .gpt_neox import GPTNeoXLayeredApply
    from .gptj import GPTJLayeredApply
    from .llama import LlamaLayeredApply
    from .opt import OPTLayeredApply

    return {
        "LlamaForCausalLM": LlamaLayeredApply,
        "GPTNeoXForCausalLM": GPTNeoXLayeredApply,
        "GPTJForCausalLM": GPTJLayeredApply,
        "OPTForCausalLM": OPTLayeredApply,
    }


def layered_for_family(family: str, config):
    """Construct the family's `LayeredApply` from a config alone — no module,
    no weights. `split()` is pure pytree indexing, so the plan CLI can split an
    `eval_shape` tree and plan a 3D pipeline layout without materializing."""
    cls_name = LAYERED_BY_FAMILY.get(family)
    if cls_name is None:
        raise ValueError(
            f"Family {family!r} ships no LayeredApply decomposition — pipeline-"
            f"parallel planning needs one (known: {sorted(LAYERED_BY_FAMILY)}). "
            "Drop the 'pipeline' mesh axis for this model."
        )
    return _layered_classes()[cls_name](config)


def layered_for_model(model):
    """The model's `LayeredApply` decomposition, sniffed from its flax module.

    Returns the constructed LayeredApply instance, or raises ValueError when
    the model has no module / the family ships no decomposition — the caller
    (3D planner dispatch) turns that into "this model can't pipeline"."""
    module = getattr(model, "module", None)
    cls_name = type(module).__name__ if module is not None else None
    layered_cls = _layered_classes().get(cls_name or "")
    if layered_cls is None:
        known = sorted(LAYERED_BY_FAMILY.values())
        raise ValueError(
            f"No LayeredApply decomposition for module {cls_name!r} — pipeline-"
            f"parallel planning/execution needs one (known: {known}). Pass "
            "layered= explicitly or drop the 'pipeline' mesh axis."
        )
    return layered_cls(module.config)


def get_model_family(name: str):
    """(interchange family, dataclass config) for a named in-tree model."""
    key = name.lower()
    if key not in MODEL_REGISTRY:
        raise ValueError(f"Unknown in-tree model {name!r}; known: {sorted(MODEL_REGISTRY)}")
    family, factory = MODEL_REGISTRY[key]
    return family, factory()


def create_named_model(name: str, **kwargs):
    """Build the Model bundle for a registry name (create fn resolved by family)."""
    family, config = get_model_family(name)
    return CREATE_BY_FAMILY[family](config, **kwargs)


def _t5_cfg(c: T5Config) -> dict:
    return {
        "model_type": "t5",
        "vocab_size": c.vocab_size,
        "hidden_size": c.d_model,
        "d_ff": c.d_ff,
        "d_kv": c.d_kv,
        "head_dim": c.d_kv,
        "num_hidden_layers": c.num_layers + c.num_decoder_layers,
        "num_encoder_layers": c.num_layers,
        "num_decoder_layers": c.num_decoder_layers,
        "num_attention_heads": c.num_heads,
        "intermediate_size": c.d_ff,
        "is_encoder_decoder": True,
        "feed_forward_proj": c.feed_forward_proj,
        "tie_word_embeddings": c.tie_word_embeddings,
    }


def _gpt_neox_cfg(c: GPTNeoXConfig) -> dict:
    return {
        "model_type": "gpt_neox",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "intermediate_size": c.intermediate_size,
        "rotary_pct": c.rotary_pct,
        "tie_word_embeddings": False,
    }


def _opt_cfg(c: OPTConfig) -> dict:
    return {
        "model_type": "opt",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "intermediate_size": c.intermediate_size,
        "tie_word_embeddings": True,
    }


def _gptj_cfg(c: GPTJConfig) -> dict:
    return {
        "model_type": "gptj",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "intermediate_size": c.intermediate_size,
        "rotary_dim": c.rotary_dim,
        "tie_word_embeddings": False,
    }


def _mixtral_cfg(c: MixtralConfig) -> dict:
    return {
        "model_type": "mixtral",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "num_key_value_heads": c.num_key_value_heads,
        "intermediate_size": c.intermediate_size,
        "num_local_experts": c.num_local_experts,
        "num_experts_per_tok": c.num_experts_per_tok,
        "hidden_act": "silu",
        "tie_word_embeddings": False,
    }


def _bert_cfg(c: BertConfig) -> dict:
    return {
        "model_type": "bert",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "intermediate_size": c.intermediate_size,
        "tie_word_embeddings": True,
    }


def _llama_cfg(c: LlamaConfig) -> dict:
    return {
        "model_type": "llama",
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_hidden_layers,
        "num_attention_heads": c.num_attention_heads,
        "num_key_value_heads": c.num_key_value_heads,
        "intermediate_size": c.intermediate_size,
        "hidden_act": "silu",
        "tie_word_embeddings": c.tie_word_embeddings,
    }


# family -> HF-shaped dict builder (bare references; defined above this point).
_CFG_BUILDERS = {
    "bert": _bert_cfg,
    "llama": _llama_cfg,
    "mixtral": _mixtral_cfg,
    "gptj": _gptj_cfg,
    "gpt_neox": _gpt_neox_cfg,
    "opt": _opt_cfg,
    "t5": _t5_cfg,
}


def get_model_config(name: str) -> dict:
    """HF-config.json-shaped dict for a named in-tree model (estimate CLI)."""
    family, config = get_model_family(name)
    return _CFG_BUILDERS[family](config)

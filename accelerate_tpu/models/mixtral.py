"""Mixtral-family sparse-MoE decoder: Llama backbone with a top-k expert-parallel FFN.

The MoE model family the reference can only reach through DeepSpeed-MoE leaf modules
(dataclasses.py:992-1010); here it's in-tree with first-class expert-axis sharding
(parallel/expert.py). The backbone (RMSNorm, RoPE, GQA attention) is shared with
models/llama.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..parallel.expert import EXPERT_SHARDING_RULES, MoEBlock
from ..ops.remat import maybe_remat
from .llama import LlamaAttention, LlamaConfig, RMSNorm

MIXTRAL_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"embed_tokens/embedding", ("model", None)),
    (r"lm_head/kernel", (None, "model")),
    (r"router/kernel", ()),  # tiny; replicate
] + EXPERT_SHARDING_RULES


@dataclass
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    max_position_embeddings: int = 32768
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    router_aux_loss_coef: float = 0.02
    router_z_loss_coef: float = 0.001
    # Serving: >0 routes the shared LlamaAttention through the KV-cache path
    # (Generator sets it via dataclasses.replace, same as every causal family).
    decode_cache_length: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def as_llama(self) -> LlamaConfig:
        """Attention-relevant view for the shared backbone modules."""
        return LlamaConfig(
            vocab_size=self.vocab_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rope_theta=self.rope_theta,
            rms_norm_eps=self.rms_norm_eps,
            decode_cache_length=self.decode_cache_length,
        )


class MixtralLayer(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        attn = LlamaAttention(cfg.as_llama(), name="attention")(
            RMSNorm(cfg.rms_norm_eps, name="input_norm")(hidden), positions, mask
        )
        hidden = hidden + attn
        moe_out, aux = MoEBlock(
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_experts=cfg.num_local_experts,
            top_k=cfg.num_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            name="moe",
        )(RMSNorm(cfg.rms_norm_eps, name="post_attn_norm")(hidden))
        return hidden + moe_out, aux


class MixtralForCausalLM(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, positions=None, return_aux: bool = False):
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens")(input_ids)
        total_aux = {"load_balance_loss": jnp.float32(0.0), "router_z_loss": jnp.float32(0.0)}
        Layer = maybe_remat(MixtralLayer)
        for i in range(cfg.num_hidden_layers):
            hidden, aux = Layer(cfg, name=f"layer_{i}")(hidden, positions, attention_mask)
            total_aux = {k: total_aux[k] + aux[k] for k in total_aux}
        hidden = RMSNorm(cfg.rms_norm_eps, name="final_norm")(hidden)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head")(hidden)
        if return_aux:
            n = jnp.float32(max(cfg.num_hidden_layers, 1))
            return logits, {k: v / n for k, v in total_aux.items()}
        return logits


def make_moe_causal_lm_loss(config: "MixtralConfig"):
    """Next-token cross-entropy + router load-balance/z losses (the Mixtral objective)."""

    def moe_causal_lm_loss(params, batch, apply_fn):
        logits, aux = apply_fn(
            params, batch["input_ids"], batch.get("attention_mask"), return_aux=True
        )
        labels = batch.get("labels", batch["input_ids"])
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = labels[:, 1:]
        logp = jax.nn.log_softmax(shift_logits, axis=-1)
        valid = (shift_labels >= 0).astype(jnp.float32)
        safe_labels = jnp.maximum(shift_labels, 0)
        nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        ce = (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)
        total = (
            ce
            + config.router_aux_loss_coef * aux["load_balance_loss"]
            + config.router_z_loss_coef * aux["router_z_loss"]
        )
        return total, {"ce": ce, **aux}

    return moe_causal_lm_loss


def create_mixtral_model(config: Optional[MixtralConfig] = None, rng=None, seq_len: int = 2048) -> Model:
    config = config or mixtral_tiny()
    if rng is None:
        rng = jax.random.key(0)
    module = MixtralForCausalLM(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), dtype=jnp.int32)
    params = module.init(rng, sample)
    return Model.from_flax(
        module,
        params,
        loss_fn=make_moe_causal_lm_loss(config),
        sharding_rules=MIXTRAL_SHARDING_RULES,
    )


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def mixtral_tiny() -> MixtralConfig:
    """Test-size config."""
    return MixtralConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
    )

"""Llama-family decoder in flax — the flagship FSDP model (BASELINE.json:
"Llama-3-8B full-shard fine-tune on TPU mesh" / big_model_inference Llama-70B).

Fresh flax implementation: RMSNorm (fp32 accumulation), rotary embeddings, grouped-query
attention through the shared attention seam, SwiGLU MLP, optional `lax.scan` over layers
(one compiled layer body — faster compiles for deep stacks), and Megatron-layout TP
rules + FSDP-friendly shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import (
    dot_product_attention,
    slot_cache_attention,
    update_decode_cache,
)

from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat

# The hand-written Megatron layout. Since the sharding planner landed
# (parallel/planner.py, sharding_rules="auto") this table is the parity
# ORACLE the planner is tested against, not the required source — the auto
# plan must match or beat it on modeled cost with identical greedy tokens.
LLAMA_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"(w_gate|w_up)/kernel", (None, "model")),
    (r"w_down/kernel", ("model", None)),
    (r"embed_tokens/embedding", ("model", None)),
    (r"lm_head/kernel", (None, "model")),
]


@dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    scan_layers: bool = False
    # When set, attention keeps a [B, decode_cache_length] KV cache in the flax
    # "cache" collection (incremental decoding); 0 = normal training/forward path.
    decode_cache_length: int = 0
    # Slot-batched serving (serving.ContinuousBatcher): every batch row is an
    # independent request slot whose decode position comes from the `positions`
    # argument (per-row scatter writes) instead of the shared `cache_index`.
    decode_slot_cache: bool = False
    # Paged slot cache: K/V live in one pool of decode_num_pages fixed-size
    # pages ([num_pages, page_size, h, d]) instead of a dense row per slot, and
    # the per-slot page tables ride in through the `attention_mask` argument as
    # [B, pages_per_slot] int32 traced operands (slot decode never carries a
    # boolean mask, so the seam is free). 0 = contiguous per-slot rows.
    decode_page_size: int = 0
    decode_num_pages: int = 0
    # Serving-decode attention implementation (paged slot cache only):
    # "xla" = gather the slot's pages into a logical buffer then attend (the
    # parity oracle); "pallas_paged" = the ops/paged_attention kernels, which
    # walk the page table inside the kernel and never materialize the gather.
    # Threaded from serving.ContinuousBatcher(attention_impl=...).
    decode_attention_impl: str = "xla"
    # KV page-pool storage dtype (paged slot cache only): "bf16" keeps the
    # model compute dtype; "int8"/"fp8_e4m3" store pages quantized with
    # per-page-per-head scale pools riding in the cache collection
    # (ops/quantization.py). Threaded from ContinuousBatcher(kv_cache_dtype=).
    decode_kv_cache_dtype: str = "bf16"
    # Weight storage dtype for the serving programs: "int8" runs every Dense
    # whose kernel is a quantized entry (quantize_params_int8) through the
    # fused int8-epilogue matmul via the weight_autocast interceptor.
    weight_dtype: str = "bf16"
    # Tensor-parallel decode submesh (serving.ContinuousBatcher(tp=N)): the
    # 1-axis ("model",) jax Mesh the engine's sharded executables span. The
    # XLA paths need nothing (GSPMD partitions them off the operand
    # shardings); the Pallas page-walk kernels read this to shard_map over
    # the KV-head grid, since pallas_call has no GSPMD partitioning rule.
    # None = single-device serving, byte-for-byte the pre-TP behavior.
    decode_tp_mesh: Optional[Any] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def rotary_embedding(x, positions, theta: float):
    """Apply RoPE to [B, S, H, D] given [B, S] positions."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        b, s, _ = hidden.shape
        hq, hkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        q = nn.Dense(hq * d, use_bias=False, name="wq")(hidden).reshape(b, s, hq, d)
        k = nn.Dense(hkv * d, use_bias=False, name="wk")(hidden).reshape(b, s, hkv, d)
        v = nn.Dense(hkv * d, use_bias=False, name="wv")(hidden).reshape(b, s, hkv, d)
        q = rotary_embedding(q, positions, cfg.rope_theta)
        k = rotary_embedding(k, positions, cfg.rope_theta)

        if cfg.decode_cache_length:
            if cfg.decode_slot_cache:
                # Continuous-batching decode: each slot row writes at its OWN
                # position (per-row scatter) and attends its written prefix
                # only. Paged mode reads `mask` as the slot page table ([B,
                # pages_per_slot] int32) mapping positions onto pool pages;
                # decode_attention_impl picks the XLA gather oracle or the
                # fused Pallas page-walk kernels.
                out = slot_cache_attention(
                    self, q, k, v, cfg.decode_cache_length, positions,
                    page_table=mask if cfg.decode_page_size else None,
                    page_size=cfg.decode_page_size,
                    num_pages=cfg.decode_num_pages,
                    attention_impl=cfg.decode_attention_impl,
                    kv_cache_dtype=cfg.decode_kv_cache_dtype,
                    mesh=cfg.decode_tp_mesh,
                )
            else:
                # Incremental decoding through the shared flax-cache write path
                # (ops/attention.update_decode_cache).
                k_all, v_all, decode_mask = update_decode_cache(self, k, v, cfg.decode_cache_length, pad_mask=mask)
                out = dot_product_attention(q, k_all, v_all, mask=decode_mask, causal=False)
        else:
            out = dot_product_attention(q, k, v, mask=mask, causal=True)
        return nn.Dense(cfg.hidden_size, use_bias=False, name="wo")(out.reshape(b, s, hq * d))


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, name="w_gate")(hidden)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, name="w_up")(hidden)
        return nn.Dense(cfg.hidden_size, use_bias=False, name="w_down")(nn.silu(gate) * up)


class LlamaLayer(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        attn = LlamaAttention(cfg, name="attention")(RMSNorm(cfg.rms_norm_eps, name="input_norm")(hidden), positions, mask)
        hidden = constrain_activation(hidden + attn)
        mlp = LlamaMLP(cfg, name="mlp")(RMSNorm(cfg.rms_norm_eps, name="post_attn_norm")(hidden))
        return constrain_activation(hidden + mlp)


class _ScanLayerBody(nn.Module):
    """nn.scan body: carry = hidden, (positions, mask) broadcast, no per-step output."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, carry, positions, mask):
        return LlamaLayer(self.config, name="layer")(carry, positions, mask), None


class LlamaForCausalLM(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, positions=None):
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = constrain_activation(nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens")(input_ids))
        if cfg.scan_layers:
            # One compiled layer body scanned over a stacked param axis — the
            # compile-time answer to deep stacks (XLA sees a single layer).
            scan_layer = nn.scan(
                maybe_remat(_ScanLayerBody),
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers,
            )
            hidden, _ = scan_layer(cfg, name="layers")(hidden, positions, attention_mask)
        else:
            Layer = maybe_remat(LlamaLayer)
            for i in range(cfg.num_hidden_layers):
                hidden = Layer(cfg, name=f"layer_{i}")(hidden, positions, attention_mask)
        hidden = RMSNorm(cfg.rms_norm_eps, name="final_norm")(hidden)
        if cfg.tie_word_embeddings:
            embed = self.variables["params"]["embed_tokens"]["embedding"]
            return hidden @ embed.T
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head")(hidden)


def causal_lm_loss(params, batch, apply_fn):
    """Next-token cross-entropy with shift; ignores positions where labels < 0."""
    logits = apply_fn(params, batch["input_ids"], batch.get("attention_mask"))
    labels = batch.get("labels", batch["input_ids"])
    shift_logits = logits[:, :-1].astype(jnp.float32)
    shift_labels = labels[:, 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    valid = (shift_labels >= 0).astype(jnp.float32)
    safe_labels = jnp.maximum(shift_labels, 0)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def create_llama_model(
    config: Optional[LlamaConfig] = None, rng=None, seq_len: int = 2048, param_dtype=None
) -> Model:
    config = config or llama_tiny()
    if rng is None:
        rng = jax.random.key(0)
    module = LlamaForCausalLM(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), dtype=jnp.int32)
    params = module.init(rng, sample)
    if param_dtype is not None:
        dtype = jnp.dtype(param_dtype)
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, params
        )
    return Model.from_flax(module, params, loss_fn=causal_lm_loss, sharding_rules=LLAMA_SHARDING_RULES)


class LlamaLayeredApply:
    """LayeredApply protocol for layer-streamed big-model inference
    (accelerate_tpu.big_modeling): run Llama models larger than HBM by streaming one
    layer's weights at a time while the previous layer computes."""

    def __init__(self, config: LlamaConfig):
        self.config = config

    def _layer_names(self, params):
        inner = params["params"]
        return sorted(
            (k for k in inner if k.startswith("layer_") and k != "layers"),
            key=lambda s: int(s.split("_")[1]),
        )

    def split(self, params):
        import jax

        inner = params["params"]
        prelude = {"params": {"embed_tokens": inner["embed_tokens"]}}
        if "layers" in inner:
            # scan_layers=True: stacked [L, ...] params under layers/layer; slice one
            # layer per step.
            stacked = inner["layers"]["layer"]
            layers = [
                {"params": jax.tree_util.tree_map(lambda x: x[i], stacked)}
                for i in range(self.config.num_hidden_layers)
            ]
        else:
            layers = [{"params": inner[name]} for name in self._layer_names(params)]
        tail_keys = {"final_norm"} | ({"lm_head"} if "lm_head" in inner else set())
        if self.config.tie_word_embeddings:
            # Tied head: the tail needs the embedding matrix for hidden @ E^T.
            tail_keys.add("embed_tokens")
        tail = {"params": {k: inner[k] for k in tail_keys if k in inner}}
        return prelude, layers, tail

    def join(self, prelude, layers, tail):
        inner = dict(prelude["params"])
        for i, lp in enumerate(layers):
            inner[f"layer_{i}"] = lp["params"]
        inner.update(tail["params"])
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, attention_mask=None):
        cfg = self.config
        b, s = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens").apply(
            {"params": {"embedding": prelude_params["params"]["embed_tokens"]["embedding"]}}, input_ids
        )
        return (hidden, positions, attention_mask)

    def apply_layer(self, layer_params, carry):
        hidden, positions, mask = carry
        hidden = LlamaLayer(self.config).apply(layer_params, hidden, positions, mask)
        return (hidden, positions, mask)

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        hidden, _, _ = carry
        hidden = RMSNorm(cfg.rms_norm_eps).apply({"params": tail_params["params"]["final_norm"]}, hidden)
        if "lm_head" in tail_params["params"]:
            return nn.Dense(cfg.vocab_size, use_bias=False).apply(
                {"params": tail_params["params"]["lm_head"]}, hidden
            )
        if cfg.tie_word_embeddings:
            embed = tail_params["params"]["embed_tokens"]["embedding"]
            return hidden @ embed.T
        return hidden


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_70b() -> LlamaConfig:
    """The big-model-inference flagship size (BASELINE.json: Llama-3-70B
    device_map='auto' across pod)."""
    return LlamaConfig(
        hidden_size=8192,
        intermediate_size=28672,
        num_hidden_layers=80,
        num_attention_heads=64,
        num_key_value_heads=8,
    )


def mistral_7b() -> LlamaConfig:
    """Mistral-7B dims (BASELINE.json: ZeRO-3→GSPMD config). Same decoder family;
    sliding-window attention degenerates to full attention at seq <= 4096."""
    return LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        max_position_embeddings=32768,
        rope_theta=1000000.0,
    )


def llama_1b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_hidden_layers=16,
        num_attention_heads=32,
        num_key_value_heads=8,
    )


def llama_tiny() -> LlamaConfig:
    """Test-size config."""
    return LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
    )

"""GPT-J family decoder in flax — the reference's big-model-inference headline
architecture (benchmarks/README.md:31: GPT-J-6B fp16, 0.05 s/token on 2x Titan RTX;
driver benchmarks/big_model_inference.py). Implementing it natively lets bench.py's
inference mode measure the SAME model configuration the reference publishes.

Architecture (vs Llama): parallel residual block — `x + attn(ln(x)) + mlp(ln(x))`
with ONE LayerNorm per block; partial rotary (first `rotary_dim` dims of each head);
standard LayerNorm with bias; biased MLP + lm_head, un-biased QKV/out projections;
full multi-head attention (no GQA). Shares the attention seam (`ops/attention`) and
the KV-cache pattern with the Llama family, so decode/flash dispatch and the
Generator work unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import dot_product_attention, update_decode_cache
from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat
from .llama import causal_lm_loss

GPTJ_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"fc_in/kernel", (None, "model")),
    (r"fc_out/kernel", ("model", None)),
    (r"wte/embedding", ("model", None)),
    (r"lm_head/kernel", (None, "model")),
]


@dataclass
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 4096
    intermediate_size: int = 16384
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    rotary_dim: int = 64
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    scan_layers: bool = False
    decode_cache_length: int = 0  # same contract as LlamaConfig
    # Parameter STORAGE dtype. "bfloat16" initializes params directly in bf16 —
    # required to even instantiate gptj_6b on a 16GB-HBM chip (an f32 init tree
    # would be 24GB before any cast).
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def _pdtype(self):
        return jnp.dtype(self.param_dtype)


def partial_rotary(x, positions, rotary_dim: int):
    """GPT-J RoPE variant: rotate only the first `rotary_dim` dims of each head,
    pass the rest through. GPT-J interleaves even/odd dims (rotate_every_two)
    rather than splitting in halves like Llama."""
    rot, pass_through = x[..., :rotary_dim], x[..., rotary_dim:]
    inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, rot/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1 = rot.astype(jnp.float32)[..., ::2]
    x2 = rot.astype(jnp.float32)[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).reshape(rot.shape)
    return jnp.concatenate([out.astype(x.dtype), pass_through], axis=-1)


class GPTJAttention(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        b, s, _ = hidden.shape
        h, d = cfg.num_attention_heads, cfg.head_dim
        q = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wq")(hidden).reshape(b, s, h, d)
        k = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wk")(hidden).reshape(b, s, h, d)
        v = nn.Dense(h * d, use_bias=False, param_dtype=cfg._pdtype, name="wv")(hidden).reshape(b, s, h, d)
        q = partial_rotary(q, positions, cfg.rotary_dim)
        k = partial_rotary(k, positions, cfg.rotary_dim)

        if cfg.decode_cache_length:
            L = cfg.decode_cache_length
            k_all, v_all, decode_mask = update_decode_cache(self, k, v, L, pad_mask=mask)
            out = dot_product_attention(q, k_all, v_all, mask=decode_mask, causal=False)
        else:
            out = dot_product_attention(q, k, v, mask=mask, causal=True)
        return nn.Dense(cfg.hidden_size, use_bias=False, param_dtype=cfg._pdtype, name="wo")(out.reshape(b, s, h * d))


class GPTJMLP(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        return nn.Dense(cfg.hidden_size, param_dtype=cfg._pdtype, name="fc_out")(
            nn.gelu(nn.Dense(cfg.intermediate_size, param_dtype=cfg._pdtype, name="fc_in")(hidden))
        )


class GPTJBlock(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        # Parallel residual: one LN feeds BOTH branches; their outputs add to the
        # residual stream together (GPT-J's signature structure).
        normed = nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="ln_1")(hidden)
        attn = GPTJAttention(cfg, name="attention")(normed, positions, mask)
        mlp = GPTJMLP(cfg, name="mlp")(normed)
        return constrain_activation(hidden + attn + mlp)


class _ScanBlockBody(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, carry, positions, mask):
        return GPTJBlock(self.config, name="block")(carry, positions, mask), None


class GPTJForCausalLM(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, positions=None):
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = constrain_activation(
            nn.Embed(cfg.vocab_size, cfg.hidden_size, param_dtype=cfg._pdtype, name="wte")(input_ids)
        )
        if cfg.scan_layers:
            scan_block = nn.scan(
                maybe_remat(_ScanBlockBody),
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers,
            )
            hidden, _ = scan_block(cfg, name="blocks")(hidden, positions, attention_mask)
        else:
            Block = maybe_remat(GPTJBlock)
            for i in range(cfg.num_hidden_layers):
                hidden = Block(cfg, name=f"layer_{i}")(hidden, positions, attention_mask)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="ln_f")(hidden)
        return nn.Dense(cfg.vocab_size, param_dtype=cfg._pdtype, name="lm_head")(hidden)  # biased, per GPT-J


def create_gptj_model(
    config: Optional[GPTJConfig] = None, rng=None, seq_len: int = 2048, param_dtype=None
) -> Model:
    import dataclasses

    config = config or gptj_tiny()
    if param_dtype is not None:
        # Threaded into the module (not cast after init) so a 6B model never
        # materializes an f32 tree: peak init memory is the bf16 params plus one
        # f32 temp for the largest single param.
        config = dataclasses.replace(config, param_dtype=str(jnp.dtype(param_dtype)))
    if rng is None:
        rng = jax.random.key(0)
    module = GPTJForCausalLM(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), dtype=jnp.int32)
    params = jax.jit(module.init)(rng, sample)
    return Model.from_flax(module, params, loss_fn=causal_lm_loss, sharding_rules=GPTJ_SHARDING_RULES)


class GPTJLayeredApply:
    """LayeredApply protocol for layer-streamed big-model inference (same protocol
    as LlamaLayeredApply): runs GPT-J/NeoX-class models larger than HBM by
    streaming one block's weights at a time."""

    def __init__(self, config: GPTJConfig):
        self.config = config

    def _layer_names(self, params):
        inner = params["params"]
        return sorted(
            (k for k in inner if k.startswith("layer_")),
            key=lambda s: int(s.split("_")[1]),
        )

    def split(self, params):
        inner = params["params"]
        prelude = {"params": {"wte": inner["wte"]}}
        if "blocks" in inner:
            stacked = inner["blocks"]["block"]
            layers = [
                {"params": jax.tree_util.tree_map(lambda x: x[i], stacked)}
                for i in range(self.config.num_hidden_layers)
            ]
        else:
            layers = [{"params": inner[name]} for name in self._layer_names(params)]
        tail = {"params": {k: inner[k] for k in ("ln_f", "lm_head") if k in inner}}
        return prelude, layers, tail

    def join(self, prelude, layers, tail):
        inner = dict(prelude["params"])
        for i, lp in enumerate(layers):
            inner[f"layer_{i}"] = lp["params"]
        inner.update(tail["params"])
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, attention_mask=None):
        cfg = self.config
        b, s = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="wte").apply(
            {"params": {"embedding": prelude_params["params"]["wte"]["embedding"]}}, input_ids
        )
        return (hidden, positions, attention_mask)

    def apply_layer(self, layer_params, carry):
        hidden, positions, mask = carry
        hidden = GPTJBlock(self.config).apply(layer_params, hidden, positions, mask)
        return (hidden, positions, mask)

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        hidden, _, _ = carry
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps).apply(
            {"params": tail_params["params"]["ln_f"]}, hidden
        )
        return nn.Dense(cfg.vocab_size).apply({"params": tail_params["params"]["lm_head"]}, hidden)


def gptj_6b() -> GPTJConfig:
    """EleutherAI GPT-J-6B dims (the reference's benchmarks/README.md:31 headline)."""
    return GPTJConfig()


def gptj_tiny() -> GPTJConfig:
    """Test-size config."""
    return GPTJConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        rotary_dim=16,
        max_position_embeddings=256,
    )

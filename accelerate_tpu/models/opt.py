"""OPT family decoder in flax — the reference's 30B big-model-inference config
(benchmarks/README.md:36-37: OPT-30B, 2.37 s/token fp16 CPU-offload / 33.9 s/token
fp32 disk-offload on 2x Titan RTX). The CPU/disk-offload rows are exactly the tiered
execution big_modeling.py replaces with overlapped layer streaming.

Architecture: pre-LN transformer with LEARNED position embeddings (with OPT's
historical +2 index offset), biased q/k/v/out and fc1/fc2, ReLU activation, and the
lm_head tied to the token embedding."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import dot_product_attention, update_decode_cache
from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat
from .llama import causal_lm_loss

OPT_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"fc1/kernel", (None, "model")),
    (r"fc2/kernel", ("model", None)),
    (r"embed_tokens/embedding", ("model", None)),
]

# OPT's learned position table is indexed at position+2 (a legacy of fairseq's
# padding-token bookkeeping); the table itself has max_position_embeddings + 2 rows.
POSITION_OFFSET = 2


@dataclass
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 7168
    intermediate_size: int = 28672
    num_hidden_layers: int = 48
    num_attention_heads: int = 56
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    scan_layers: bool = False
    decode_cache_length: int = 0
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def _pdtype(self):
        return jnp.dtype(self.param_dtype)


class OPTAttention(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        b, s, _ = hidden.shape
        h, d = cfg.num_attention_heads, cfg.head_dim
        q = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wq")(hidden).reshape(b, s, h, d)
        k = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wk")(hidden).reshape(b, s, h, d)
        v = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wv")(hidden).reshape(b, s, h, d)

        if cfg.decode_cache_length:
            L = cfg.decode_cache_length
            k_all, v_all, decode_mask = update_decode_cache(self, k, v, L, pad_mask=mask)
            out = dot_product_attention(q, k_all, v_all, mask=decode_mask, causal=False)
        else:
            out = dot_product_attention(q, k, v, mask=mask, causal=True)
        return nn.Dense(cfg.hidden_size, param_dtype=cfg._pdtype, name="wo")(out.reshape(b, s, h * d))


class OPTBlock(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        # Pre-LN (do_layer_norm_before=True, the configuration of every OPT >= 350m).
        attn = OPTAttention(cfg, name="attention")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="self_attn_norm")(hidden),
            positions,
            mask,
        )
        hidden = constrain_activation(hidden + attn)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="final_norm")(hidden)
        x = nn.relu(nn.Dense(cfg.intermediate_size, param_dtype=cfg._pdtype, name="fc1")(x))
        x = nn.Dense(cfg.hidden_size, param_dtype=cfg._pdtype, name="fc2")(x)
        return constrain_activation(hidden + x)


class _ScanBlockBody(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, carry, positions, mask):
        return OPTBlock(self.config, name="block")(carry, positions, mask), None


class OPTForCausalLM(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, positions=None):
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, param_dtype=cfg._pdtype, name="embed_tokens")
        pos_embed = nn.Embed(
            cfg.max_position_embeddings + POSITION_OFFSET,
            cfg.hidden_size,
            param_dtype=cfg._pdtype,
            name="embed_positions",
        )
        hidden = constrain_activation(embed(input_ids) + pos_embed(positions + POSITION_OFFSET))
        if cfg.scan_layers:
            scan_block = nn.scan(
                maybe_remat(_ScanBlockBody),
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers,
            )
            hidden, _ = scan_block(cfg, name="blocks")(hidden, positions, attention_mask)
        else:
            Block = maybe_remat(OPTBlock)
            for i in range(cfg.num_hidden_layers):
                hidden = Block(cfg, name=f"layer_{i}")(hidden, positions, attention_mask)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="final_norm")(hidden)
        # Tied head: logits against the token embedding (OPT ties by default).
        embedding = self.variables["params"]["embed_tokens"]["embedding"]
        return hidden @ embedding.T.astype(hidden.dtype)


def create_opt_model(
    config: Optional[OPTConfig] = None, rng=None, seq_len: int = 2048, param_dtype=None
) -> Model:
    import dataclasses

    config = config or opt_tiny()
    if param_dtype is not None:
        config = dataclasses.replace(config, param_dtype=str(jnp.dtype(param_dtype)))
    if rng is None:
        rng = jax.random.key(0)
    module = OPTForCausalLM(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), dtype=jnp.int32)
    params = jax.jit(module.init)(rng, sample)
    return Model.from_flax(module, params, loss_fn=causal_lm_loss, sharding_rules=OPT_SHARDING_RULES)


class OPTLayeredApply:
    """LayeredApply protocol for tier-streamed execution of the 30B config
    (the reference's CPU/disk-offload benchmark rows)."""

    def __init__(self, config: OPTConfig):
        self.config = config

    def _layer_names(self, params):
        inner = params["params"]
        return sorted((k for k in inner if k.startswith("layer_")), key=lambda s: int(s.split("_")[1]))

    def split(self, params):
        inner = params["params"]
        prelude = {"params": {k: inner[k] for k in ("embed_tokens", "embed_positions")}}
        if "blocks" in inner:
            stacked = inner["blocks"]["block"]
            layers = [
                {"params": jax.tree_util.tree_map(lambda x: x[i], stacked)}
                for i in range(self.config.num_hidden_layers)
            ]
        else:
            layers = [{"params": inner[name]} for name in self._layer_names(params)]
        # Tied head: the tail re-uses the embedding from the prelude, so split()
        # duplicates the reference into both (join() keeps one copy).
        tail = {"params": {"final_norm": inner["final_norm"], "embed_tokens": inner["embed_tokens"]}}
        return prelude, layers, tail

    def join(self, prelude, layers, tail):
        inner = dict(prelude["params"])
        for i, lp in enumerate(layers):
            inner[f"layer_{i}"] = lp["params"]
        inner["final_norm"] = tail["params"]["final_norm"]
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, attention_mask=None):
        cfg = self.config
        b, s = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        inner = prelude_params["params"]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size).apply(
            {"params": {"embedding": inner["embed_tokens"]["embedding"]}}, input_ids
        )
        pos = nn.Embed(cfg.max_position_embeddings + POSITION_OFFSET, cfg.hidden_size).apply(
            {"params": {"embedding": inner["embed_positions"]["embedding"]}}, positions + POSITION_OFFSET
        )
        return (embed + pos, positions, attention_mask)

    def apply_layer(self, layer_params, carry):
        hidden, positions, mask = carry
        hidden = OPTBlock(self.config).apply(layer_params, hidden, positions, mask)
        return (hidden, positions, mask)

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        hidden, _, _ = carry
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps).apply(
            {"params": tail_params["params"]["final_norm"]}, hidden
        )
        embedding = tail_params["params"]["embed_tokens"]["embedding"]
        return hidden @ embedding.T.astype(hidden.dtype)


def opt_30b() -> OPTConfig:
    """facebook/opt-30b dims (reference benchmarks/README.md:36-37)."""
    return OPTConfig()


def opt_tiny() -> OPTConfig:
    return OPTConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        max_position_embeddings=256,
    )

"""GPT-NeoX family decoder in flax — the reference's 20B big-model-inference config
(benchmarks/README.md:33-34: GPT-NeoX-20B, 0.08 s/token fp16 / 10.72 s/token fp32
disk-offload on 2x Titan RTX). The 20B size is the flagship case for layer-streamed
execution (big_modeling.py): 40GB of bf16 weights against 16GB of HBM.

Architecture: parallel residual `x + attn(ln_1(x)) + mlp(ln_2(x))` with TWO
LayerNorms per block (vs GPT-J's one); partial rotary in Llama's half-split style
(rotary_pct of each head, NOT GPT-J's interleaved pairs); biased QKV/out/MLP
projections; un-biased lm_head (embed_out)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..modeling import Model
from ..ops.attention import (
    dot_product_attention,
    slot_cache_attention,
    update_decode_cache,
)
from ..parallel.sharding import constrain_activation
from ..ops.remat import maybe_remat
from .llama import causal_lm_loss

# Parity oracle for the sharding planner (see LLAMA_SHARDING_RULES).
GPT_NEOX_SHARDING_RULES = [
    (r"(wq|wk|wv)/kernel", (None, "model")),
    (r"wo/kernel", ("model", None)),
    (r"dense_h_to_4h/kernel", (None, "model")),
    (r"dense_4h_to_h/kernel", ("model", None)),
    (r"embed_in/embedding", ("model", None)),
    (r"embed_out/kernel", (None, "model")),
]


@dataclass
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    intermediate_size: int = 24576
    num_hidden_layers: int = 44
    num_attention_heads: int = 64
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    scan_layers: bool = False
    decode_cache_length: int = 0
    # Per-row slot-cache decode for continuous batching (see LlamaConfig).
    decode_slot_cache: bool = False
    # Paged slot cache: pool geometry + page tables via the mask seam (see
    # LlamaConfig for the full semantics).
    decode_page_size: int = 0
    decode_num_pages: int = 0
    # Serving-decode attention implementation (see LlamaConfig): "xla" gather
    # oracle or the "pallas_paged" fused page-walk kernels.
    decode_attention_impl: str = "xla"
    # Quantized serving (see LlamaConfig): KV page-pool storage dtype and
    # weight storage dtype for the serving programs.
    decode_kv_cache_dtype: str = "bf16"
    weight_dtype: str = "bf16"
    # Tensor-parallel decode submesh (see LlamaConfig.decode_tp_mesh): the
    # 1-axis ("model",) Mesh the Pallas page-walk kernels shard_map over.
    decode_tp_mesh: Optional[Any] = None
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_ndims(self) -> int:
        return int(self.head_dim * self.rotary_pct)

    @property
    def _pdtype(self):
        return jnp.dtype(self.param_dtype)


def neox_partial_rotary(x, positions, rotary_ndims: int, theta: float):
    """NeoX RoPE: rotate the first `rotary_ndims` dims of each head in the
    HALF-SPLIT style (rotate_half, like Llama), pass the rest through."""
    rot, pass_through = x[..., :rotary_ndims], x[..., rotary_ndims:]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, rotary_ndims, 2, dtype=jnp.float32) / rotary_ndims))
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(rot.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([out.astype(x.dtype), pass_through], axis=-1)


class GPTNeoXAttention(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        b, s, _ = hidden.shape
        h, d = cfg.num_attention_heads, cfg.head_dim
        q = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wq")(hidden).reshape(b, s, h, d)
        k = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wk")(hidden).reshape(b, s, h, d)
        v = nn.Dense(h * d, param_dtype=cfg._pdtype, name="wv")(hidden).reshape(b, s, h, d)
        q = neox_partial_rotary(q, positions, cfg.rotary_ndims, cfg.rope_theta)
        k = neox_partial_rotary(k, positions, cfg.rotary_ndims, cfg.rope_theta)

        if cfg.decode_cache_length:
            L = cfg.decode_cache_length
            if cfg.decode_slot_cache:
                # Continuous-batching decode: per-row scatter writes at each
                # slot's own position (serving.ContinuousBatcher). Paged mode
                # reads `mask` as the [B, pages_per_slot] int32 page table;
                # decode_attention_impl picks the gather oracle or the fused
                # Pallas page-walk kernels.
                out = slot_cache_attention(
                    self, q, k, v, L, positions,
                    page_table=mask if cfg.decode_page_size else None,
                    page_size=cfg.decode_page_size,
                    num_pages=cfg.decode_num_pages,
                    attention_impl=cfg.decode_attention_impl,
                    kv_cache_dtype=cfg.decode_kv_cache_dtype,
                    mesh=cfg.decode_tp_mesh,
                )
            else:
                k_all, v_all, decode_mask = update_decode_cache(self, k, v, L, pad_mask=mask)
                out = dot_product_attention(q, k_all, v_all, mask=decode_mask, causal=False)
        else:
            out = dot_product_attention(q, k, v, mask=mask, causal=True)
        return nn.Dense(cfg.hidden_size, param_dtype=cfg._pdtype, name="wo")(out.reshape(b, s, h * d))


class GPTNeoXMLP(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        # exact (erf) gelu: NeoX's hidden_act is "gelu", not the tanh "gelu_new"
        # GPT-J uses — approximate=True here would drift from the HF reference.
        return nn.Dense(cfg.hidden_size, param_dtype=cfg._pdtype, name="dense_4h_to_h")(
            nn.gelu(
                nn.Dense(cfg.intermediate_size, param_dtype=cfg._pdtype, name="dense_h_to_4h")(hidden),
                approximate=False,
            )
        )


class GPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden, positions, mask):
        cfg = self.config
        attn = GPTNeoXAttention(cfg, name="attention")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="input_norm")(hidden),
            positions,
            mask,
        )
        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x)) — two norms, one residual add.
            mlp = GPTNeoXMLP(cfg, name="mlp")(
                nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="post_attn_norm")(hidden)
            )
            return constrain_activation(hidden + attn + mlp)
        hidden = hidden + attn
        mlp = GPTNeoXMLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="post_attn_norm")(hidden)
        )
        return constrain_activation(hidden + mlp)


class _ScanBlockBody(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, carry, positions, mask):
        return GPTNeoXBlock(self.config, name="block")(carry, positions, mask), None


class GPTNeoXForCausalLM(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, positions=None):
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = constrain_activation(
            nn.Embed(cfg.vocab_size, cfg.hidden_size, param_dtype=cfg._pdtype, name="embed_in")(input_ids)
        )
        if cfg.scan_layers:
            scan_block = nn.scan(
                maybe_remat(_ScanBlockBody),
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers,
            )
            hidden, _ = scan_block(cfg, name="blocks")(hidden, positions, attention_mask)
        else:
            Block = maybe_remat(GPTNeoXBlock)
            for i in range(cfg.num_hidden_layers):
                hidden = Block(cfg, name=f"layer_{i}")(hidden, positions, attention_mask)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, param_dtype=cfg._pdtype, name="final_norm")(hidden)
        return nn.Dense(cfg.vocab_size, use_bias=False, param_dtype=cfg._pdtype, name="embed_out")(hidden)


def create_gpt_neox_model(
    config: Optional[GPTNeoXConfig] = None, rng=None, seq_len: int = 2048, param_dtype=None
) -> Model:
    import dataclasses

    config = config or gpt_neox_tiny()
    if param_dtype is not None:
        config = dataclasses.replace(config, param_dtype=str(jnp.dtype(param_dtype)))
    if rng is None:
        rng = jax.random.key(0)
    module = GPTNeoXForCausalLM(config)
    sample = jnp.zeros((1, min(seq_len, config.max_position_embeddings)), dtype=jnp.int32)
    params = jax.jit(module.init)(rng, sample)
    return Model.from_flax(module, params, loss_fn=causal_lm_loss, sharding_rules=GPT_NEOX_SHARDING_RULES)


class GPTNeoXLayeredApply:
    """LayeredApply protocol — the 20B config's route to running inside 16GB of HBM
    via layer streaming (big_modeling.DispatchedModel)."""

    def __init__(self, config: GPTNeoXConfig):
        self.config = config

    def _layer_names(self, params):
        inner = params["params"]
        return sorted((k for k in inner if k.startswith("layer_")), key=lambda s: int(s.split("_")[1]))

    def split(self, params):
        inner = params["params"]
        prelude = {"params": {"embed_in": inner["embed_in"]}}
        if "blocks" in inner:
            stacked = inner["blocks"]["block"]
            layers = [
                {"params": jax.tree_util.tree_map(lambda x: x[i], stacked)}
                for i in range(self.config.num_hidden_layers)
            ]
        else:
            layers = [{"params": inner[name]} for name in self._layer_names(params)]
        tail = {"params": {k: inner[k] for k in ("final_norm", "embed_out") if k in inner}}
        return prelude, layers, tail

    def join(self, prelude, layers, tail):
        inner = dict(prelude["params"])
        for i, lp in enumerate(layers):
            inner[f"layer_{i}"] = lp["params"]
        inner.update(tail["params"])
        return {"params": inner}

    def apply_prelude(self, prelude_params, input_ids, attention_mask=None):
        cfg = self.config
        b, s = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size).apply(
            {"params": {"embedding": prelude_params["params"]["embed_in"]["embedding"]}}, input_ids
        )
        return (hidden, positions, attention_mask)

    def apply_layer(self, layer_params, carry):
        hidden, positions, mask = carry
        hidden = GPTNeoXBlock(self.config).apply(layer_params, hidden, positions, mask)
        return (hidden, positions, mask)

    def apply_tail(self, tail_params, carry):
        cfg = self.config
        hidden, _, _ = carry
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps).apply(
            {"params": tail_params["params"]["final_norm"]}, hidden
        )
        return nn.Dense(cfg.vocab_size, use_bias=False).apply(
            {"params": tail_params["params"]["embed_out"]}, hidden
        )


def gpt_neox_20b() -> GPTNeoXConfig:
    """EleutherAI GPT-NeoX-20B dims (reference benchmarks/README.md:33)."""
    return GPTNeoXConfig()


def gpt_neox_tiny() -> GPTNeoXConfig:
    return GPTNeoXConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        max_position_embeddings=256,
    )

"""On-demand profiler capture: programmatic `jax.profiler` sessions you can
trigger on a LIVE run.

The r05 bench hang was unexplainable after the fact because profiling here was
two ad-hoc context managers you had to wrap around code *in advance*.
`ProfilerManager` owns the profiler lifecycle so a capture can be demanded from
outside at the moment something looks wrong:

  - **touch-file trigger**: `touch <log_dir>/CAPTURE` on the host (over ssh,
    from a watchdog script like tpu_watch_r05.sh) — the next `poll()` at a step
    boundary consumes the file and opens a fixed-duration trace window;
  - **signal trigger**: SIGUSR2 latches a capture request (same degrade-to-warn
    off the main thread as `fault_tolerance.PreemptionHandler`);
  - **fixed-duration windows**: a triggered capture stops itself after
    `capture_seconds` of wall clock (checked at `poll()` boundaries), so an
    unattended trigger can never fill the disk with an unbounded xplane dump;
  - **device-memory snapshots**: `save_memory_snapshot()` dumps the pprof HBM
    profile next to the traces.

`Accelerator` polls its manager every fused train step and wires
``ACCELERATE_TPU_PROFILE_DIR`` (the `accelerate-tpu launch --profile_dir` env
protocol) through `from_env`, so worker processes inherit the launch flag. The
jax.profiler calls live behind an injectable backend both for tests and so
importing this module never touches jax.
"""

from __future__ import annotations

import contextlib
import os
import signal as _signal
import threading
import time
from typing import Optional

from ..logging import get_logger
from .metrics import MetricsRegistry

logger = get_logger(__name__)

#: Name of the trigger file inside ``log_dir`` (touch it to request a capture).
TOUCH_FILE_NAME = "CAPTURE"


class _JaxProfilerBackend:
    """The real profiler: thin calls into jax.profiler, imported lazily."""

    def start_trace(self, log_dir: str):
        import jax

        jax.profiler.start_trace(log_dir)

    def stop_trace(self):
        import jax

        jax.profiler.stop_trace()

    def save_device_memory_profile(self, path: str):
        import jax

        jax.profiler.save_device_memory_profile(path)


class ProfilerManager:
    """Owns programmatic profiler sessions for one process.

    Disabled (``log_dir=None``) every method is a cheap no-op — constructing a
    manager unconditionally (as `Accelerator` does) costs nothing when
    profiling wasn't requested. ``poll()`` is the step-boundary hook: it
    consumes pending triggers and closes expired capture windows; its fast path
    (no capture armed, no trigger) is two attribute reads and one `os.path`
    probe every `poll_every` calls.
    """

    def __init__(
        self,
        log_dir: Optional[str] = None,
        capture_seconds: float = 10.0,
        touch_file: Optional[str] = None,
        poll_every: int = 10,
        registry: Optional[MetricsRegistry] = None,
        backend=None,
        clock=time.monotonic,
    ):
        self.log_dir = str(log_dir) if log_dir else None
        if self.log_dir:
            # The touch-file contract is "touch <log_dir>/CAPTURE on a live
            # run": the directory must exist the moment the manager is armed,
            # not at first capture.
            os.makedirs(self.log_dir, exist_ok=True)
        self.capture_seconds = float(capture_seconds)
        self.touch_file = touch_file or (
            os.path.join(self.log_dir, TOUCH_FILE_NAME) if self.log_dir else None
        )
        self.poll_every = max(1, int(poll_every))
        self.registry = registry if registry is not None else MetricsRegistry()
        self._backend = backend if backend is not None else _JaxProfilerBackend()
        self._clock = clock
        self._lock = threading.Lock()
        self._active = False
        self._deadline: Optional[float] = None
        self._capture_index = 0
        self._polls = 0
        self._signal_latch = threading.Event()
        self._signal_installed = False
        self._captures = self.registry.counter(
            "profiler_captures_total", help="profiler trace windows opened"
        )
        self._active_gauge = self.registry.gauge(
            "profiler_active", help="1 while a trace window is open"
        )
        self._memory_snapshots = self.registry.counter(
            "profiler_memory_snapshots_total", help="device-memory profiles dumped"
        )

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    @property
    def active(self) -> bool:
        return self._active

    @classmethod
    def from_env(
        cls,
        default_dir: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        install_signal: bool = True,
        **kwargs,
    ) -> "ProfilerManager":
        """Build from the launch env protocol: ``ACCELERATE_TPU_PROFILE_DIR``
        (set by `accelerate-tpu launch --profile_dir`) wins over `default_dir`.
        When the env var armed the manager, the SIGUSR2 trigger is installed
        too — the launch flag means "this run should be profilable from
        outside"."""
        env_dir = os.environ.get("ACCELERATE_TPU_PROFILE_DIR")
        manager = cls(log_dir=env_dir or default_dir, registry=registry, **kwargs)
        if env_dir and install_signal:
            manager.install_signal_handler()
        return manager

    # ---------------------------------------------------------------- triggers
    def install_signal_handler(self, signum: int = _signal.SIGUSR2) -> bool:
        """SIGUSR2 latches a capture request served at the next `poll()`.
        Off the main thread (`signal.signal`'s restriction) this degrades to a
        warn + False — never crash the run it is meant to observe."""
        if not self.enabled or self._signal_installed:
            return self._signal_installed
        try:
            _signal.signal(signum, lambda _s, _f: self._signal_latch.set())
            self._signal_installed = True
        except ValueError:
            logger.warning(
                "ProfilerManager signal trigger disabled (not on the main thread); "
                "the touch-file trigger (%s) still works",
                self.touch_file,
            )
        return self._signal_installed

    def request_capture(self):
        """Programmatic trigger: the next `poll()` opens a capture window."""
        self._signal_latch.set()

    def _consume_trigger(self) -> bool:
        if self._signal_latch.is_set():
            self._signal_latch.clear()
            return True
        if self.touch_file and os.path.exists(self.touch_file):
            try:
                os.remove(self.touch_file)
            except OSError:
                pass  # another process raced the removal; the capture still runs
            return True
        return False

    # ----------------------------------------------------------------- windows
    def start(self, duration_s: Optional[float] = None, subdir: Optional[str] = None) -> Optional[str]:
        """Open a trace window (no-op returning None when disabled or already
        capturing). With `duration_s`, `poll()` closes it once the window
        elapses; without, it stays open until `stop()`."""
        if not self.enabled:
            return None
        with self._lock:
            if self._active:
                return None
            self._capture_index += 1
            name = subdir or f"capture_{self._capture_index:03d}"
            target = os.path.join(self.log_dir, name)
            os.makedirs(target, exist_ok=True)
            self._backend.start_trace(target)
            self._active = True
            self._deadline = (
                self._clock() + float(duration_s) if duration_s is not None else None
            )
        self._captures.inc()
        self._active_gauge.set(1)
        logger.info("profiler capture started -> %s", target)
        return target

    def stop(self) -> bool:
        """Close the open window (idempotent)."""
        with self._lock:
            if not self._active:
                return False
            self._backend.stop_trace()
            self._active = False
            self._deadline = None
        self._active_gauge.set(0)
        logger.info("profiler capture stopped")
        return True

    def poll(self) -> bool:
        """Step-boundary hook: close an expired window, else serve a pending
        trigger with a fixed `capture_seconds` window. Trigger probes run every
        `poll_every` calls (an os.path.exists per step would tax tight decode
        loops); expiry is checked every call so windows close promptly.
        Returns True when a capture is open after the poll."""
        if not self.enabled:
            return False
        if self._active:
            deadline = self._deadline
            if deadline is not None and self._clock() >= deadline:
                self.stop()
            return self._active
        self._polls += 1
        if self._polls % self.poll_every and not self._signal_latch.is_set():
            return False
        if self._consume_trigger():
            self.start(duration_s=self.capture_seconds)
        return self._active

    @contextlib.contextmanager
    def trace(self, subdir: Optional[str] = None):
        """Scoped capture (the `Accelerator.profile` surface): opens a window
        for the block, always closes it. No-op when disabled."""
        target = self.start(subdir=subdir)
        try:
            yield target
        finally:
            if target is not None:
                self.stop()

    # --------------------------------------------------------------- snapshots
    def save_memory_snapshot(self, path: Optional[str] = None) -> Optional[str]:
        """Dump the device-memory (HBM) profile in pprof format — works whether
        or not a trace window is open. Default path lands next to the traces."""
        if path is None:
            if not self.enabled:
                return None
            path = os.path.join(self.log_dir, f"memory_{self._capture_index:03d}.prof")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._backend.save_device_memory_profile(path)
        self._memory_snapshots.inc()
        return path

"""Crash/hang flight recorder: the bounded span ring buffer and its dumpers.

The r05 incident burned ~21 minutes on an opaque hang with nothing to explain
it afterwards — the process had been doing *something*, but the evidence died
with it. The `FlightRecorder` keeps the last `capacity` completed spans and
instant events in memory (bounded forever, like the metrics histograms) and
turns them into artifacts at exactly the moments evidence is about to vanish:

  - **on demand** — ``accelerate-tpu trace dump`` touches ``<dir>/DUMP``; the
    next `poll()` at a step/chunk boundary consumes it and writes a Perfetto
    trace-event JSON (the same touch-file pattern as the profiler's CAPTURE);
  - **on exit / SIGTERM** — `install_exit_hooks()` registers an atexit dump
    and a chaining SIGTERM handler, so a clean shutdown or a preemption still
    leaves a timeline behind;
  - **on a hang** — the `HangWatchdog` thread fires when no step-boundary
    heartbeat lands within `deadline_s`: it dumps the trace tail plus
    ALL-thread stack traces (`sys._current_frames`), turning the next
    r05-style stall into an artifact instead of a mystery.

When armed with a ``log_dir`` the recorder additionally *streams* every
record to ``spans_<pid>.jsonl`` the moment it lands (flushed line-by-line,
like the chaos journal): a SIGKILL tears at most the line in flight, and the
spans written before the kill — including the ``span_start`` record of
whatever was open when the process died — survive as the crash boundary the
chaos ``trace_complete`` invariant reconciles.

Pure stdlib; jax is never imported here.
"""

from __future__ import annotations

import atexit
import json
import os
import signal as _signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, List, Optional

from ..logging import get_logger
from .metrics import MetricsRegistry

logger = get_logger(__name__)

#: Touch this file inside ``log_dir`` to request a dump at the next poll().
DUMP_TOUCH_FILE = "DUMP"


def read_span_jsonl(path: str) -> List[dict]:
    """Read one streamed span file, skipping blank and torn lines (a killed
    writer tears at most the final line; the reader must never crash on it)."""
    records: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return records
    return records


def collect_trace_dir(log_dir: str) -> List[dict]:
    """Every record streamed into a trace dir (all processes), in time order —
    the stitched raw material for export and the chaos invariant checks."""
    records: List[dict] = []
    if not os.path.isdir(log_dir):
        return records
    for name in sorted(os.listdir(log_dir)):
        if name.startswith("spans_") and name.endswith(".jsonl"):
            records.extend(read_span_jsonl(os.path.join(log_dir, name)))
    records.sort(key=lambda r: r.get("start_unix", r.get("t_unix", 0.0)))
    return records


def format_thread_stacks() -> str:
    """Every live thread's current stack — what the process was doing RIGHT
    NOW. This is the payload a hang dump needs: the r05 postmortem's missing
    artifact was exactly 'where was the main thread blocked'."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        lines.extend(line.rstrip("\n") for line in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


class FlightRecorder:
    """Bounded ring of completed spans/events + the dump machinery.

    In-memory by default (`log_dir=None`): `record()` is a lock + deque append,
    cheap enough to ride every request. With a `log_dir`, records also stream
    to ``spans_<pid>.jsonl`` and the touch-file/exit/watchdog dumpers arm.
    """

    def __init__(
        self,
        capacity: int = 4096,
        log_dir: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        poll_every: int = 16,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.log_dir = str(log_dir) if log_dir else None
        self.registry = registry if registry is not None else MetricsRegistry()
        self.poll_every = max(1, int(poll_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._stream = None
        self._dump_index = 0
        self._polls = 0
        self._exit_hooks_installed = False
        self.watchdog: Optional[HangWatchdog] = None
        self._m_recorded = self.registry.counter(
            "trace_spans_recorded_total", help="spans/events accepted by the flight recorder"
        )
        self._m_evicted = self.registry.counter(
            "trace_spans_evicted_total", help="records pushed out of the bounded ring"
        )
        self._m_dumps = self.registry.counter(
            "trace_dumps_total", help="trace artifacts written (manual/touch/exit/hang)"
        )
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)

    # ------------------------------------------------------------------ intake
    def _stream_write(self, record: dict):
        if self.log_dir is None:
            return
        with self._lock:
            if self._stream is None:
                path = os.path.join(self.log_dir, f"spans_{os.getpid()}.jsonl")
                self._stream = open(path, "a")
            self._stream.write(json.dumps(record) + "\n")
            # Flush per record (no fsync: a span stream is evidence, not a
            # durability contract — the chaos journal owns fsync'd truth).
            self._stream.flush()

    def on_span_start(self, record: dict):
        """Streamed immediately so an open span survives a SIGKILL as its
        start record; NOT ring-buffered (the completed span supersedes it)."""
        self._stream_write(record)

    def record(self, record: dict):
        """Accept one completed span / instant event (a plain dict — the
        recorder never holds live Span objects, so the ring is snapshot-safe)."""
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._m_evicted.inc()
            self._ring.append(record)
        self._m_recorded.inc()
        self._stream_write(record)

    def records(self) -> List[dict]:
        """Ring contents, oldest first (eviction order is arrival order)."""
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------------ dumping
    @property
    def touch_file(self) -> Optional[str]:
        return os.path.join(self.log_dir, DUMP_TOUCH_FILE) if self.log_dir else None

    def dump(self, path: Optional[str] = None, reason: str = "manual") -> Optional[str]:
        """Write the ring as Chrome/Perfetto trace-event JSON. Default path is
        ``<log_dir>/trace_<pid>_<n>.json``; with neither a path nor a log_dir
        there is nowhere to dump (returns None)."""
        from .export import write_trace_events  # lazy: export pulls checkpointing

        if path is None:
            if self.log_dir is None:
                return None
            self._dump_index += 1
            path = os.path.join(
                self.log_dir, f"trace_{os.getpid()}_{self._dump_index:03d}.json"
            )
        write_trace_events(self.records(), path)
        self._m_dumps.inc()
        logger.info("flight recorder dumped %d record(s) -> %s (%s)", len(self), path, reason)
        return path

    def poll(self) -> bool:
        """Step/chunk-boundary hook: consume a pending ``DUMP`` touch file.
        The fast path is one counter increment every call and one
        `os.path.exists` every `poll_every` calls (the profiler's cadence)."""
        if self.log_dir is None:
            return False
        self._polls += 1
        if self._polls % self.poll_every:
            return False
        touch = self.touch_file
        if touch and os.path.exists(touch):
            try:
                os.remove(touch)
            except OSError:
                pass  # another process raced the removal; still dump
            self.dump(reason="touch-file")
            return True
        return False

    # ------------------------------------------------------------------ exit hooks
    def install_exit_hooks(self, catch_sigterm: bool = True) -> "FlightRecorder":
        """Dump on interpreter exit and (chained) on SIGTERM. The SIGTERM hook
        preserves whatever handler was installed before it — including the
        `PreemptionHandler` latch — by calling it after the dump; installed off
        the main thread it degrades to atexit-only (the signal module's
        restriction, same as the profiler trigger)."""
        if self._exit_hooks_installed or self.log_dir is None:
            return self
        self._exit_hooks_installed = True
        atexit.register(self._dump_on_exit)
        if catch_sigterm:
            try:
                prev = _signal.getsignal(_signal.SIGTERM)

                def handler(signum, frame):
                    self.dump(reason="sigterm")
                    if callable(prev):
                        prev(signum, frame)
                    elif prev == _signal.SIG_DFL:
                        _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                        os.kill(os.getpid(), _signal.SIGTERM)

                _signal.signal(_signal.SIGTERM, handler)
            except ValueError:
                logger.warning(
                    "flight recorder SIGTERM dump disabled (not on the main thread); "
                    "atexit and touch-file dumps still work"
                )
        return self

    def _dump_on_exit(self):
        if len(self):
            try:
                self.dump(reason="exit")
            except Exception:  # noqa: BLE001 — never turn shutdown into a crash
                logger.warning("flight recorder exit dump failed", exc_info=True)

    def close(self):
        with self._lock:
            if self._stream is not None:
                self._stream.close()
                self._stream = None

    # ------------------------------------------------------------------ watchdog
    def heartbeat(self):
        """Step-boundary liveness signal (forwards to the watchdog if armed)."""
        if self.watchdog is not None:
            self.watchdog.heartbeat()

    def start_watchdog(
        self,
        deadline_s: float,
        tracer=None,
        poll_interval_s: float = 1.0,
        clock: Optional[Callable[[], float]] = None,
        start_thread: bool = True,
    ) -> "HangWatchdog":
        """Arm the hang watchdog: if no `heartbeat()` lands within
        `deadline_s`, dump the trace tail + all-thread stacks. One watchdog
        per recorder; re-arming returns the existing one."""
        if self.watchdog is None:
            self.watchdog = HangWatchdog(
                self,
                deadline_s=deadline_s,
                tracer=tracer,
                poll_interval_s=poll_interval_s,
                clock=clock or self._clock,
            )
            if start_thread:
                self.watchdog.start()
        return self.watchdog


class HangWatchdog:
    """Fires when the instrumented loop stops heartbeating.

    The firing is one-shot per stall: after a dump, the watchdog waits for the
    next heartbeat before it can fire again (a 30-minute hang must produce one
    readable artifact, not 1800 of them). The deadline ARMS at the first
    heartbeat — warmup (backend init, the first compiles) legitimately runs
    minutes before the instrumented loop starts, and compile completions count
    as liveness too (the compile-event listener heartbeats), so "hang" means
    the loop went silent MID-RUN. `check_once()` is the synchronous evaluation
    (what the thread loop calls; tests drive it with a FakeClock).
    """

    def __init__(self, recorder: FlightRecorder, deadline_s: float,
                 tracer=None, poll_interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.recorder = recorder
        self.deadline_s = float(deadline_s)
        self.tracer = tracer
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock
        self._last_beat: Optional[float] = None  # armed by the first heartbeat
        self._fired_for_current_stall = False
        self.fired_count = 0
        self.last_dump: Optional[str] = None
        self.last_stacks_path: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def heartbeat(self):
        self._last_beat = self._clock()
        self._fired_for_current_stall = False

    def stalled_s(self) -> float:
        if self._last_beat is None:
            return 0.0  # never armed: warmup is not a stall
        return self._clock() - self._last_beat

    def check_once(self) -> bool:
        """Evaluate the deadline now; fire (dump trace + stacks) on expiry.
        Returns True when this call fired."""
        stalled = self.stalled_s()
        if stalled < self.deadline_s or self._fired_for_current_stall:
            return False
        self._fired_for_current_stall = True
        self.fired_count += 1
        self._fire(stalled)
        return True

    def _fire(self, stalled: float):
        logger.warning(
            "hang watchdog: no step heartbeat for %.1fs (deadline %.1fs) — dumping "
            "trace tail and thread stacks", stalled, self.deadline_s,
        )
        if self.tracer is not None:
            # The event lands in the ring (and the stream) BEFORE the dump, so
            # the dump itself contains the hang marker.
            self.tracer.event(
                "hang.detected", category="watchdog",
                stalled_s=round(stalled, 3), deadline_s=self.deadline_s,
            )
        stacks = format_thread_stacks()
        if self.recorder.log_dir:
            stacks_path = os.path.join(
                self.recorder.log_dir, f"hang_{os.getpid()}_{self.fired_count:03d}.txt"
            )
            with open(stacks_path, "w") as f:
                f.write(
                    f"hang watchdog fired: {stalled:.3f}s without a step heartbeat "
                    f"(deadline {self.deadline_s:.3f}s)\n\n"
                )
                f.write(stacks)
        else:
            stacks_path = None
        self.last_dump = self.recorder.dump(reason="hang") or stacks_path
        self.last_stacks_path = stacks_path

    # ------------------------------------------------------------------ thread
    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="trace-hang-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the watchdog must outlive its own bugs
                logger.warning("hang watchdog check failed", exc_info=True)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

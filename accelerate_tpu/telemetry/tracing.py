"""Request-scoped distributed tracing: spans over the serve/train hot paths.

The metrics registry (PR 4) answers "how is the system doing on average"; this
module answers "what happened to THIS request" and "what was the process doing
at second partition". A `Tracer` creates `Span`s — named, attributed intervals on a
monotonic host clock — and hands every finished span to a recorder (the
bounded ring buffer in `flight_recorder.py`), from which Chrome/Perfetto
trace-event JSON is produced on demand.

The same discipline as `metrics.py` applies, because spans ride the decode and
train step loops:

  - **zero device syncs**: span timestamps are `time.monotonic()` arithmetic
    and span attributes/events accept HOST values only (str/int/float/bool/
    None). A jax array reaching an annotation raises `TypeError` before it can
    hide a blocking readback — the runtime half of lint rule TPU112.
  - **no jax import**: this module is pure stdlib, so host-side tools (the
    `accelerate-tpu trace` CLI, the chaos runner's invariant checks) can read
    and stitch traces without an accelerator stack.
  - **bounded memory**: the tracer itself holds only the active-span stack;
    completed spans go to the recorder's fixed-capacity ring.

Cross-process causality uses the launch env protocol (the same two-sided
pattern as ``ACCELERATE_TPU_FAULT_PLAN`` / ``ACCELERATE_TPU_PROFILE_DIR``):

  - ``ACCELERATE_TPU_TRACE_DIR``    — arm a file-backed recorder (streamed
    span JSONL + on-demand/exit dumps), set by ``launch --trace_dir``;
  - ``ACCELERATE_TPU_TRACE_ID``     — the shared trace id, minted once by the
    launcher/supervisor so every restart stitches into ONE timeline;
  - ``ACCELERATE_TPU_TRACE_PARENT`` — the parent span id (the supervisor's
    attempt span), so a worker's root spans parent under the attempt that
    spawned them.

Timestamps are recorded on the monotonic clock (durations are exact, immune
to NTP steps) with a per-tracer unix anchor taken ONCE at construction, so
spans from different processes land on one comparable timeline when stitched.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: Env vars of the cross-process trace protocol (mirrors ACCELERATE_TPU_FAULT_PLAN).
TRACE_DIR_ENV = "ACCELERATE_TPU_TRACE_DIR"
TRACE_ID_ENV = "ACCELERATE_TPU_TRACE_ID"
TRACE_PARENT_ENV = "ACCELERATE_TPU_TRACE_PARENT"

#: Attribute value types a span accepts — host data only, the TPU112 gate.
_HOST_TYPES = (str, bool, int, float, type(None))


def _check_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """The zero-device-sync gate for span annotations: only host values may
    enter a span. A jax array serialized here would force a blocking
    device->host readback on the hot path (exactly what lint rule TPU112
    flags statically) — reject it loudly instead of silently syncing."""
    for key, value in attrs.items():
        if not isinstance(value, _HOST_TYPES):
            raise TypeError(
                f"span annotations take host values (str/int/float/bool/None), got "
                f"{type(value).__name__} for {key!r}: read device values at the step "
                "boundary (np.asarray/.item()) BEFORE annotating — an implicit "
                "conversion here would hide a device sync"
            )
    return dict(attrs)


def new_id() -> str:
    """A 12-hex-char id, unique across processes (no coordination needed)."""
    return os.urandom(6).hex()


class Span:
    """One named interval: monotonic start/end, host-only attributes, and
    in-span instant events. Created through a `Tracer`; `end()` hands the
    completed record to the tracer's recorder (idempotent)."""

    __slots__ = (
        "name", "category", "trace_id", "span_id", "parent_id",
        "start_s", "end_s", "attrs", "events", "_tracer", "_ended",
    )

    def __init__(self, tracer: "Tracer", name: str, category: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.category = category
        self.trace_id = tracer.trace_id
        self.span_id = new_id()
        self.parent_id = parent_id
        self.start_s = tracer._clock()
        self.end_s: Optional[float] = None
        self.attrs = _check_attrs(attrs)
        self.events: List[dict] = []
        self._tracer = tracer
        self._ended = False

    def annotate(self, **attrs):
        """Attach host-value attributes (later keys win)."""
        self.attrs.update(_check_attrs(attrs))
        return self

    def event(self, name: str, **attrs):
        """Record an instant event inside this span (serialized with it)."""
        self.events.append({
            "name": name,
            "t_unix": self._tracer._anchor + self._tracer._clock(),
            "attrs": _check_attrs(attrs),
        })
        return self

    def end(self):
        """Close the span and hand it to the recorder. Idempotent — a span
        double-ended by defensive cleanup records exactly once."""
        if self._ended:
            return self
        self._ended = True
        self.end_s = self._tracer._clock()
        self._tracer._record(self)
        return self

    def to_dict(self) -> dict:
        tracer = self._tracer
        record = {
            "kind": "span",
            "name": self.name,
            "cat": self.category,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "pid": tracer.pid,
            "tid": threading.get_ident(),
            "start_unix": tracer._anchor + self.start_s,
            "end_unix": tracer._anchor + (self.end_s if self.end_s is not None else self.start_s),
            "duration_s": (self.end_s - self.start_s) if self.end_s is not None else 0.0,
            "attrs": dict(self.attrs),
        }
        if self.events:
            record["events"] = list(self.events)
        return record

    def start_record(self) -> dict:
        """The streamed-at-open record: everything known at span start. A span
        whose end never lands (SIGKILL mid-flight) survives as this record —
        the crash-boundary evidence the chaos `trace_complete` invariant
        reads."""
        tracer = self._tracer
        return {
            "kind": "span_start",
            "name": self.name,
            "cat": self.category,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "pid": tracer.pid,
            "tid": threading.get_ident(),
            "start_unix": tracer._anchor + self.start_s,
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Creates spans and standalone events, tracks the per-thread active-span
    stack (nesting -> parent ids), and feeds a recorder.

    Scoped use (the common form)::

        with tracer.span("serve.decode_chunk", slots=3) as span:
            out = chunk_fn(...)
            span.annotate(tokens=drained)

    Request-lifecycle use (a span outliving any one call frame)::

        span = tracer.start_span("serve.request", request_id=7)
        ...                       # many step() calls later
        span.annotate(finish_reason="eos").end()

    The recorder is any object with ``on_span_start(dict)``/``record(dict)``
    — in practice a `flight_recorder.FlightRecorder`. ``clock`` is injectable
    (chaos `FakeClock`) and must be monotonic.
    """

    def __init__(
        self,
        recorder=None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        category: str = "default",
        clock=time.monotonic,
        enabled: bool = True,
    ):
        from .flight_recorder import FlightRecorder  # stdlib-only sibling

        self.recorder = recorder if recorder is not None else FlightRecorder()
        self.trace_id = trace_id or new_id()
        #: Root parent for spans opened with no active span on the stack —
        #: the supervisor's attempt span id when launched under supervision.
        self.root_parent_id = parent_id
        self.category = category
        self.enabled = bool(enabled)
        self.pid = os.getpid()
        self._clock = clock
        # Unix anchor, read ONCE: wall = anchor + monotonic. All measurement
        # stays on the monotonic clock; the anchor only places this process on
        # the shared cross-process timeline at export.
        self._anchor = time.time() - clock()
        self._local = threading.local()
        self._compile_listener_installed = False

    # ------------------------------------------------------------------ context
    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def _parent_id(self, parent: Optional[Span]) -> Optional[str]:
        if parent is not None:
            return parent.span_id
        current = self.current_span
        return current.span_id if current is not None else self.root_parent_id

    # ------------------------------------------------------------------ spans
    def start_span(self, name: str, category: Optional[str] = None,
                   parent: Optional[Span] = None, **attrs) -> Span:
        """Open a span WITHOUT putting it on the context stack (request
        lifecycles, supervisor attempts). Caller owns `end()`."""
        span = Span(self, name, category or self.category, self._parent_id(parent), attrs)
        if self.enabled:
            self.recorder.on_span_start(span.start_record())
        return span

    @contextlib.contextmanager
    def span(self, name: str, category: Optional[str] = None,
             parent: Optional[Span] = None, **attrs):
        """Scoped span: pushed on this thread's stack (children nest under it),
        always ended — exceptions mark the span failed and propagate."""
        span = self.start_span(name, category=category, parent=parent, **attrs)
        stack = self._stack()
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.attrs.setdefault("error", repr(exc))
            raise
        finally:
            stack.pop()
            span.end()

    @contextlib.contextmanager
    def activate(self, span: Span):
        """Make an already-open span the context parent for the block (used to
        nest scoped spans under a long-lived lifecycle span). Does NOT end it."""
        stack = self._stack()
        stack.append(span)
        try:
            yield span
        finally:
            stack.pop()

    def event(self, name: str, category: Optional[str] = None, **attrs) -> dict:
        """A standalone instant event, recorded (and streamed) immediately —
        the right shape for chaos injections and crash boundaries, which must
        hit durable storage BEFORE the fault they describe lands."""
        record = {
            "kind": "event",
            "name": name,
            "cat": category or self.category,
            "trace_id": self.trace_id,
            "span_id": new_id(),
            "parent_id": self._parent_id(None),
            "pid": self.pid,
            "tid": threading.get_ident(),
            "t_unix": self._anchor + self._clock(),
            "attrs": _check_attrs(attrs),
        }
        if self.enabled:
            self.recorder.record(record)
        return record

    def _record(self, span: Span):
        if self.enabled:
            self.recorder.record(span.to_dict())

    # ------------------------------------------------------------------ wiring
    def attach_compile_listener(self):
        """Record every backend compile as a trace event (duration attr), via
        the same `jax.monitoring` duration hook the goodput ledger charges —
        warmup compiles then show up ON the timeline instead of as mystery
        gaps between the first steps."""
        if self._compile_listener_installed:
            return
        import jax.monitoring

        def on_duration(event: str, duration: float, **kwargs):
            if event == "/jax/core/compile/backend_compile_duration":
                self.event("backend.compile", category="compile", duration_s=float(duration))
                # A finishing compile is liveness, not a hang: keep the
                # watchdog fed while warmup retraces between the first steps.
                heartbeat = getattr(self.recorder, "heartbeat", None)
                if heartbeat is not None:
                    heartbeat()

        jax.monitoring.register_event_duration_secs_listener(on_duration)
        self._compile_listener_installed = True

    def inject_env(self, env: Dict[str, str], parent: Optional[Span] = None) -> Dict[str, str]:
        """Write the trace context into a child process env (the Supervisor →
        worker seam): trace id, parent span id, and the recorder's dir so the
        child streams into the same artifact set."""
        env[TRACE_ID_ENV] = self.trace_id
        parent_id = parent.span_id if parent is not None else (
            self.current_span.span_id if self.current_span is not None else self.root_parent_id
        )
        if parent_id:
            env[TRACE_PARENT_ENV] = parent_id
        log_dir = getattr(self.recorder, "log_dir", None)
        if log_dir:
            env[TRACE_DIR_ENV] = str(log_dir)
        return env

    @classmethod
    def from_env(cls, environ: Optional[Dict[str, str]] = None,
                 default_dir: Optional[str] = None, **kwargs) -> "Tracer":
        """Build from the launch env protocol: ``ACCELERATE_TPU_TRACE_DIR``
        arms a file-backed recorder (streamed spans + exit dumps), and the
        propagated trace/parent ids stitch this process into the launcher's
        timeline. With nothing set, the tracer still runs with an in-memory
        flight recorder — the last N spans are always available for a dump."""
        from .flight_recorder import FlightRecorder

        environ = environ if environ is not None else os.environ
        log_dir = environ.get(TRACE_DIR_ENV) or default_dir
        recorder = kwargs.pop("recorder", None)
        if recorder is None:
            recorder = FlightRecorder(log_dir=log_dir)
        return cls(
            recorder=recorder,
            trace_id=environ.get(TRACE_ID_ENV) or None,
            parent_id=environ.get(TRACE_PARENT_ENV) or None,
            **kwargs,
        )


# ---------------------------------------------------------------- default tracer
_default_lock = threading.Lock()
_default_tracer: Optional[Tracer] = None


def default_tracer() -> Tracer:
    """The process-wide tracer, built lazily from the env protocol on first
    use. Subsystems that aren't handed an explicit tracer (a bare
    `ContinuousBatcher`, an `Accelerator` outside a launch) share this one, so
    a single `trace dump` covers the whole process."""
    global _default_tracer
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = Tracer.from_env()
        return _default_tracer


def set_default_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Replace (or with None: reset) the process-wide tracer; returns the
    previous one. Tests and embedding servers use this to redirect default
    instrumentation into their own recorder."""
    global _default_tracer
    with _default_lock:
        previous, _default_tracer = _default_tracer, tracer
        return previous

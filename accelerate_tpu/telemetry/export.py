"""Metric exporters: JSONL snapshots, the Prometheus text format (file and
stdlib-HTTP ``/metrics``), the Chrome/Perfetto trace-event writer for the
flight recorder's spans, and a bridge into the `tracking.py` trackers.

Offline-first, like tracking.py: TPU pods often have no egress, so the
always-works paths are files — an append-only JSONL history a postmortem can
replay, and an atomically-replaced Prometheus textfile the standard
node-exporter ``textfile`` collector scrapes. The HTTP endpoint is optional and
pure stdlib (no prometheus_client dependency, which the image doesn't bake in).

`parse_prometheus_text` is the inverse of `to_prometheus_text` for the subset
this module emits — the round-trip is pinned by tests (and is the acceptance
criterion for the serving histograms): what a Prometheus scraper ingests is
exactly what the registry measured.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Tuple

from ..checkpointing import atomic_write
from ..logging import get_logger
from .metrics import Histogram, MetricsRegistry

logger = get_logger(__name__)


def _fmt_value(v: float) -> str:
    """Prometheus sample values: integers render bare (counter readability),
    floats in repr precision (round-trip exactness)."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n"))
        for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def to_prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in the Prometheus exposition text format.

    Histograms follow the standard encoding: cumulative ``_bucket`` series with
    ``le`` upper-bound labels (ending at ``+Inf``), plus ``_sum`` and
    ``_count``. ``# TYPE``/``# HELP`` headers are emitted once per metric name.
    """
    lines = []
    seen_headers = set()
    for inst in registry.instruments():
        if inst.name not in seen_headers:
            seen_headers.add(inst.name)
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
        if isinstance(inst, Histogram):
            cumulative = 0
            counts = inst.bucket_counts()
            for bound, count in zip(inst.bucket_bounds, counts[:-1]):
                cumulative += count
                lines.append(
                    f"{inst.name}_bucket{_fmt_labels(inst.label_dict, {'le': _fmt_value(bound)})} {cumulative}"
                )
            cumulative += counts[-1]
            lines.append(
                f"{inst.name}_bucket{_fmt_labels(inst.label_dict, {'le': '+Inf'})} {cumulative}"
            )
            lines.append(f"{inst.name}_sum{_fmt_labels(inst.label_dict)} {_fmt_value(inst.sum)}")
            lines.append(f"{inst.name}_count{_fmt_labels(inst.label_dict)} {cumulative}")
        else:
            lines.append(f"{inst.name}{_fmt_labels(inst.label_dict)} {_fmt_value(inst.value)}")
    return "\n".join(lines) + "\n"


def _unescape_label_value(value: str) -> str:
    """Decode the exposition-format escapes in ONE left-to-right pass:
    sequential str.replace would mis-decode a value containing a literal
    backslash followed by 'n' (escaped on the wire as two backslashes + n)."""
    out, i = [], 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_labels(block: str) -> Tuple[Tuple[str, str], ...]:
    labels = []
    for part in _split_label_pairs(block):
        key, _eq, raw = part.partition("=")
        value = _unescape_label_value(raw.strip()[1:-1])  # strip quotes
        labels.append((key.strip(), value))
    return tuple(sorted(labels))


def _split_label_pairs(block: str):
    """Split `a="x",b="y"` on commas outside quotes (values may contain ',')."""
    parts, buf, in_quotes, escaped = [], [], False, False
    for ch in block:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
        if ch == "," and not in_quotes:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in (s.strip() for s in parts) if p]


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse the subset `to_prometheus_text` emits back into plain data:
    ``{series_name: {"type": kind, "samples": {labels_tuple: value}}}`` where
    histogram series appear under their ``_bucket``/``_sum``/``_count`` names
    (the wire truth a scraper sees). Unknown/malformed lines are skipped with a
    warning — a parser for monitoring must never crash monitoring."""
    out: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            fields = line.split(None, 3)
            if len(fields) >= 4 and fields[1] == "TYPE":
                types[fields[2]] = fields[3]
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                label_block, value_part = rest.rsplit("}", 1)
                labels = _parse_labels(label_block)
                # host-only text parsing, no device values in sight
                value = float(value_part.strip())  # tpu-lint: disable=loop-host-sync
            else:
                name, value_part = line.rsplit(None, 1)
                labels = ()
                # host-only text parsing, no device values in sight
                value = float(value_part)  # tpu-lint: disable=loop-host-sync
        except ValueError:
            logger.warning("skipping malformed prometheus line: %r", line)
            continue
        name = name.strip()
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
                break
        series = out.setdefault(name, {"type": types.get(base, "untyped"), "samples": {}})
        series["samples"][labels] = value
    return out


def write_prometheus_textfile(registry: MetricsRegistry, path: str) -> str:
    """Atomically replace `path` with the current exposition (temp + fsync +
    rename, via checkpointing.atomic_write): a node-exporter textfile collector
    scraping mid-write sees the previous complete snapshot, never a torn one."""
    text = to_prometheus_text(registry)
    atomic_write(path, lambda f: f.write(text), mode="w")
    return path


def write_jsonl_snapshot(registry: MetricsRegistry, path: str, step: Optional[int] = None, **extra) -> dict:
    """Append one self-contained snapshot line (wall time + full registry dump)
    to a JSONL history — the postmortem format: replay the file to see every
    metric's trajectory, no scraper required."""
    record = {"time": time.time(), "metrics": registry.snapshot()}
    if step is not None:
        record["step"] = step
    record.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(record, default=str) + "\n")
    return record


# ------------------------------------------------------------------ trace events
def _us(t_unix: float) -> int:
    """Trace-event timestamps are integer microseconds."""
    return int(round(float(t_unix) * 1e6))


def _trace_args(record: dict) -> Dict[str, object]:
    """Span ids ride in `args` so Perfetto's query/selection UI can correlate
    a request across processes; user attrs come after (and win on clash is
    impossible — attr names are user-chosen, ids are namespaced)."""
    args: Dict[str, object] = {
        "trace_id": record.get("trace_id"),
        "span_id": record.get("span_id"),
    }
    if record.get("parent_id"):
        args["parent_id"] = record["parent_id"]
    args.update(record.get("attrs") or {})
    return args


def to_trace_events(records) -> dict:
    """Render flight-recorder records as a Chrome trace-event JSON object
    (the format chrome://tracing and Perfetto load directly).

    - completed spans     -> ``"ph": "X"`` complete events (ts + dur, µs);
    - in-span events      -> ``"ph": "i"`` thread-scoped instants inside them;
    - standalone events   -> ``"ph": "i"`` process-scoped instants;
    - dangling span_start -> ``"ph": "B"`` begin events with no matching end —
      Perfetto renders them as unfinished, which is exactly what a span that
      died with its process IS (the crash boundary, visually).

    Timestamps are the records' unix-anchored times, so spans streamed by a
    supervisor and three restarted workers land on ONE comparable timeline.
    """
    events = []
    seen_pids = set()
    ended = {r.get("span_id") for r in records if r.get("kind") == "span"}
    for record in records:
        kind = record.get("kind", "span")
        pid = record.get("pid", 0)
        tid = record.get("tid", 0)
        seen_pids.add(pid)
        if kind == "span":
            start = record.get("start_unix", 0.0)
            end = record.get("end_unix", start)
            events.append({
                "ph": "X",
                "name": record.get("name", "?"),
                "cat": record.get("cat", "default"),
                "ts": _us(start),
                "dur": max(_us(end) - _us(start), 0),
                "pid": pid,
                "tid": tid,
                "args": _trace_args(record),
            })
            for ev in record.get("events", ()):
                events.append({
                    "ph": "i",
                    "s": "t",
                    "name": ev.get("name", "?"),
                    "cat": record.get("cat", "default"),
                    "ts": _us(ev.get("t_unix", start)),
                    "pid": pid,
                    "tid": tid,
                    "args": dict(ev.get("attrs") or {}),
                })
        elif kind == "span_start":
            if record.get("span_id") in ended:
                continue  # the completed span supersedes its start record
            events.append({
                "ph": "B",
                "name": record.get("name", "?"),
                "cat": record.get("cat", "default"),
                "ts": _us(record.get("start_unix", 0.0)),
                "pid": pid,
                "tid": tid,
                "args": _trace_args(record),
            })
        elif kind == "event":
            events.append({
                "ph": "i",
                "s": "p",
                "name": record.get("name", "?"),
                "cat": record.get("cat", "default"),
                "ts": _us(record.get("t_unix", 0.0)),
                "pid": pid,
                "tid": tid,
                "args": _trace_args(record),
            })
    events.sort(key=lambda e: (e["pid"], e["ts"]))
    meta = [
        {
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"accelerate-tpu pid {pid}"},
        }
        for pid in sorted(seen_pids)
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_trace_events(records, path: str) -> str:
    """Atomically write records as a Perfetto-loadable trace JSON (temp +
    fsync + rename — a dump racing a crash must be whole or absent)."""
    payload = json.dumps(to_trace_events(records))
    atomic_write(path, lambda f: f.write(payload), mode="w")
    return path


class MetricsHTTPServer:
    """Optional stdlib ``/metrics`` endpoint (one daemon thread, no deps).

    ``port=0`` binds an ephemeral port (read it back from ``.port``) — the test
    and notebook default. Serving happens outside the hot path entirely: a
    scrape renders a snapshot under the instruments' own locks, so the step
    loop never blocks on a scraper (and vice versa).
    """

    def __init__(self, registry: MetricsRegistry, port: int = 0, host: str = "127.0.0.1"):
        import http.server

        self.registry = registry
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.rstrip("/") not in ("", "/metrics".rstrip("/"), "/metrics"):
                    self.send_error(404)
                    return
                body = to_prometheus_text(outer.registry).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not stderr news
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics-http", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class TrackerBridge:
    """Publish registry snapshots through the experiment trackers
    (`Accelerator.log` fan-out): counters/gauges as scalars, histograms as
    count / sum / p50 / p99 — the flattening every tracker backend can ingest.

    The bridge is pull-based (`publish(step)` at whatever cadence the loop
    likes) so tracker I/O — files, network — never rides the step hot path.
    """

    def __init__(self, accelerator, registry: Optional[MetricsRegistry] = None, prefix: str = "telemetry/"):
        self.accelerator = accelerator
        self.registry = registry if registry is not None else getattr(accelerator, "telemetry", None)
        if self.registry is None:
            raise ValueError("TrackerBridge needs a registry (or an Accelerator with .telemetry)")
        self.prefix = prefix

    def flatten(self) -> Dict[str, float]:
        values: Dict[str, float] = {}
        for inst in self.registry.instruments():
            suffix = "".join(f".{k}={v}" for k, v in sorted(inst.label_dict.items()))
            base = f"{self.prefix}{inst.name}{suffix}"
            if isinstance(inst, Histogram):
                values[f"{base}.count"] = float(inst.count)
                values[f"{base}.sum"] = inst.sum
                for q in (0.5, 0.99):
                    quantile = inst.quantile(q)
                    if quantile is not None:
                        values[f"{base}.p{int(q * 100)}"] = quantile
            else:
                values[base] = inst.value
        return values

    def publish(self, step: Optional[int] = None) -> Dict[str, float]:
        values = self.flatten()
        self.accelerator.log(values, step=step)
        return values

"""Runtime telemetry (L4 observability): metrics, step-timeline/goodput
accounting, request-scoped tracing with a crash/hang flight recorder, and
on-demand profiler capture.

Six modules, one discipline — observe the hot path without perturbing it
(host scalars only, zero device syncs, bounded memory):

  - `metrics` — process-local, thread-safe `MetricsRegistry` with
    Counter/Gauge/Histogram instruments (fixed log-spaced latency buckets).
  - `timeline` — `StepTimeline`: per-step data-wait / dispatch / sampled-block
    phase split plus the goodput ledger (checkpoint saves, restarts,
    compiles, TraceGuard recompiles) and the unaccounted-time warning.
  - `tracing` — `Tracer`/`Span`: request-scoped spans on monotonic host
    clocks, with the ``ACCELERATE_TPU_TRACE_*`` env protocol for
    cross-process (Supervisor -> worker) causality.
  - `flight_recorder` — `FlightRecorder`: the bounded span ring buffer,
    streamed span JSONL, touch-file/exit/SIGTERM dumps, and the
    `HangWatchdog` (trace tail + all-thread stacks on a stalled step).
  - `profiler` — `ProfilerManager`: programmatic `jax.profiler` sessions with
    touch-file / SIGUSR2 triggers and fixed-duration capture windows.
  - `export` — JSONL snapshots, Prometheus text (file + stdlib HTTP
    ``/metrics``), Chrome/Perfetto trace-event JSON, and the `tracking.py`
    bridge.

Importing this package never touches jax: the profiler backend, the sampled
`block_until_ready`, and the compile-event listener import lazily, so
lint-only and host-side tools (the `trace` CLI, the chaos invariant checks)
can read metrics and stitch traces without an accelerator stack.
"""

from .export import (
    MetricsHTTPServer,
    TrackerBridge,
    parse_prometheus_text,
    to_prometheus_text,
    to_trace_events,
    write_jsonl_snapshot,
    write_prometheus_textfile,
    write_trace_events,
)
from .flight_recorder import FlightRecorder, HangWatchdog, collect_trace_dir, read_span_jsonl
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_spaced_buckets,
)
from .profiler import ProfilerManager
from .timeline import StepTimeline
from .tracing import Span, Tracer, default_tracer, set_default_tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "log_spaced_buckets",
    "StepTimeline",
    "ProfilerManager",
    "Tracer",
    "Span",
    "default_tracer",
    "set_default_tracer",
    "FlightRecorder",
    "HangWatchdog",
    "collect_trace_dir",
    "read_span_jsonl",
    "MetricsHTTPServer",
    "TrackerBridge",
    "to_prometheus_text",
    "parse_prometheus_text",
    "write_prometheus_textfile",
    "write_jsonl_snapshot",
    "to_trace_events",
    "write_trace_events",
]

"""Runtime telemetry (L4 observability): metrics, step-timeline/goodput
accounting, and on-demand profiler capture.

Four modules, one discipline — observe the hot path without perturbing it
(host scalars only, zero device syncs, bounded memory):

  - `metrics` — process-local, thread-safe `MetricsRegistry` with
    Counter/Gauge/Histogram instruments (fixed log-spaced latency buckets).
  - `timeline` — `StepTimeline`: per-step data-wait / dispatch / sampled-block
    phase split plus the goodput ledger (checkpoint saves, restarts,
    compiles, TraceGuard recompiles).
  - `profiler` — `ProfilerManager`: programmatic `jax.profiler` sessions with
    touch-file / SIGUSR2 triggers and fixed-duration capture windows.
  - `export` — JSONL snapshots, Prometheus text (file + stdlib HTTP
    ``/metrics``), and the `tracking.py` bridge.

Importing this package never touches jax: the profiler backend and the
sampled `block_until_ready` import lazily, so lint-only and host-side tools
can read metrics without an accelerator stack.
"""

from .export import (
    MetricsHTTPServer,
    TrackerBridge,
    parse_prometheus_text,
    to_prometheus_text,
    write_jsonl_snapshot,
    write_prometheus_textfile,
)
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_spaced_buckets,
)
from .profiler import ProfilerManager
from .timeline import StepTimeline

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "log_spaced_buckets",
    "StepTimeline",
    "ProfilerManager",
    "MetricsHTTPServer",
    "TrackerBridge",
    "to_prometheus_text",
    "parse_prometheus_text",
    "write_prometheus_textfile",
    "write_jsonl_snapshot",
]

"""Process-local metrics registry: Counter / Gauge / Histogram instruments.

The serving engine, the Accelerator's step loop, and the bench drivers all need
the same three primitives — monotonic counts (requests finished, recompiles),
point-in-time values (queue depth, slots in use), and latency distributions
(TTFT, inter-token gaps). This module provides them with the constraints a TPU
hot path imposes:

  - **zero device syncs**: instruments accept host scalars only (perf_counter
    deltas, Python ints). Nothing here imports jax; passing a device array is a
    caller bug and raises before it can hide a blocking ``float()`` readback in
    the serving loop.
  - **bounded memory**: a Histogram is a FIXED vector of log-spaced bucket
    counts plus (sum, count) — observations are never retained individually, so
    a server can run for months without the registry growing. Quantiles are
    estimated by linear interpolation inside the owning bucket (the standard
    Prometheus-histogram estimator), accurate to the bucket resolution.
  - **thread-safe**: servers submit from request-handler threads while the
    drive loop finishes requests; every instrument guards its state with its
    own lock, and the registry locks instrument creation.

Instruments are identified by ``(name, labels)`` — the Prometheus data model —
so per-reason counters (``serving_requests_finished_total{reason="eos"}``) are
distinct time series sharing one name. Rendering/parsing of the Prometheus text
format and JSONL snapshots live in `export.py`.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

#: Prometheus metric-name charset (also enforced for label names).
_NAME_OK = lambda s: bool(s) and all(c.isalnum() or c in "_:" for c in s) and not s[0].isdigit()  # noqa: E731

#: (name, sorted labels) — one time series.
InstrumentKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _check_scalar(value) -> float:
    """The zero-device-sync gate: only host numbers may enter an instrument.

    A jax array (or anything array-like) reaching ``float()`` here would be a
    hidden blocking device->host readback on the hot path — exactly the hazard
    TPU101-103 lint for — so it is rejected loudly instead of silently syncing.
    """
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise TypeError(
            f"metrics take host scalars (int/float), got {type(value).__name__}: "
            "read device values at the step boundary (np.asarray/.item()) BEFORE "
            "recording them — an implicit conversion here would hide a device sync"
        )
    return float(value)


def log_spaced_buckets(lo: float = 1e-4, hi: float = 100.0, per_decade: int = 4) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds covering [lo, hi].

    The default — 4/decade from 100 µs to 100 s — spans everything this repo
    times (a decode chunk, a TTFT, a checkpoint save) in 25 buckets, giving
    ~78% worst-case quantile resolution per bucket at constant memory.
    """
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError("need 0 < lo < hi and per_decade >= 1")
    n = int(math.ceil(per_decade * math.log10(hi / lo)))
    bounds = [lo * 10 ** (k / per_decade) for k in range(n + 1)]
    # ceil() should land the last bound at or above hi, but float error on
    # non-integer decade spans can leave it just below — enforce coverage so
    # values in (bounds[-1], hi] can't silently fall into the +Inf overflow.
    bounds[-1] = max(bounds[-1], float(hi))
    return tuple(round(b, 12) for b in bounds)


#: The shared latency bucket layout (seconds): every latency histogram in the
#: repo uses one layout so exported series are comparable across subsystems.
DEFAULT_LATENCY_BUCKETS = log_spaced_buckets()


class _Instrument:
    """Base: identity + lock. Subclasses own their state under `self._lock`."""

    kind = "untyped"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...], help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = threading.Lock()

    @property
    def label_dict(self) -> Dict[str, str]:
        return dict(self.labels)


class Counter(_Instrument):
    """Monotonically increasing count (requests, inserts, recompiles)."""

    kind = "counter"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        amount = _check_scalar(amount)
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge for bidirectional values")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """Point-in-time value (queue depth, slots in use, goodput fraction)."""

    kind = "gauge"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def set(self, value: float):
        value = _check_scalar(value)
        with self._lock:
            self._value = value

    def inc(self, amount: float = 1.0):
        amount = _check_scalar(amount)
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-_check_scalar(amount))

    def set_max(self, value: float):
        """Retain the high-water mark (queue_peak semantics) atomically."""
        value = _check_scalar(value)
        with self._lock:
            if value > self._value:
                self._value = value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Instrument):
    """Fixed-bucket distribution: `len(buckets)+1` counts (the last is +Inf
    overflow), a running sum, and a total count — bounded memory forever."""

    kind = "histogram"

    def __init__(self, name, labels, help="", buckets: Optional[Sequence[float]] = None):
        super().__init__(name, labels, help)
        bounds = tuple(float(b) for b in (buckets if buckets is not None else DEFAULT_LATENCY_BUCKETS))
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError("histogram buckets must be a non-empty strictly-increasing sequence")
        self.bucket_bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        value = _check_scalar(value)
        idx = bisect_left(self.bucket_bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)

    def quantile(self, q: float) -> Optional[float]:
        """Prometheus-style estimate: find the bucket holding the q-th
        observation, interpolate linearly inside it. None when empty; the
        overflow bucket clamps to the top finite bound (the honest answer for
        "at least this much")."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile q must be in [0, 1]")
        with self._lock:
            counts, total = list(self._counts), self._count
        if total == 0:
            return None
        rank = q * total
        cumulative = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cumulative + c >= rank:
                if i == len(self.bucket_bounds):  # +Inf overflow
                    return self.bucket_bounds[-1]
                lower = self.bucket_bounds[i - 1] if i > 0 else 0.0
                upper = self.bucket_bounds[i]
                frac = (rank - cumulative) / c
                return lower + (upper - lower) * min(max(frac, 0.0), 1.0)
            cumulative += c
        return self.bucket_bounds[-1]


class MetricsRegistry:
    """Get-or-create instrument store keyed on (name, labels).

    One registry per subsystem owner (an `Accelerator`, a `ContinuousBatcher`)
    or shared between them — instruments are cheap and export walks whatever is
    registered. Re-requesting an existing (name, labels) returns the SAME
    instrument (so wiring code never double-counts); requesting an existing
    name as a different kind is a bug and raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[InstrumentKey, _Instrument] = {}

    @staticmethod
    def _key(name: str, labels: Optional[Dict[str, str]]) -> InstrumentKey:
        if not _NAME_OK(name):
            raise ValueError(f"invalid metric name {name!r} (want [a-zA-Z_:][a-zA-Z0-9_:]*)")
        items = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
        for k, _v in items:
            if not _NAME_OK(k):
                raise ValueError(f"invalid label name {k!r}")
        return (name, items)

    def _get_or_create(self, cls, name, labels, help, **kwargs):
        key = self._key(name, labels)
        with self._lock:
            existing = self._instruments.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {existing.kind}, not {cls.kind}"
                    )
                return existing
            instrument = cls(name, key[1], help=help, **kwargs)
            self._instruments[key] = instrument
            return instrument

    def counter(self, name: str, help: str = "", labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, labels, help)

    def gauge(self, name: str, help: str = "", labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, labels, help)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Optional[Dict[str, str]] = None,
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, labels, help, buckets=buckets)

    # ------------------------------------------------------------------ access
    def instruments(self) -> List[_Instrument]:
        """Stable-ordered view (sorted by name then labels) for exporters."""
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def get(self, name: str, labels: Optional[Dict[str, str]] = None) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(self._key(name, labels))

    def value(self, name: str, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Counter/Gauge value (histograms expose .sum/.count/.quantile)."""
        instrument = self.get(name, labels)
        return None if instrument is None or isinstance(instrument, Histogram) else instrument.value

    def snapshot(self) -> List[dict]:
        """The full registry as plain data (what JSONL export and the bench
        telemetry blocks serialize). Histograms include their bucket layout so
        a snapshot is self-describing."""
        out = []
        for inst in self.instruments():
            entry = {"name": inst.name, "kind": inst.kind, "labels": inst.label_dict}
            if inst.help:
                entry["help"] = inst.help
            if isinstance(inst, Histogram):
                entry["sum"] = inst.sum
                entry["count"] = inst.count
                entry["buckets"] = list(inst.bucket_bounds)
                entry["bucket_counts"] = inst.bucket_counts()
                for q in (0.5, 0.99):
                    quantile = inst.quantile(q)
                    if quantile is not None:
                        entry[f"p{int(q * 100)}"] = quantile
            else:
                entry["value"] = inst.value
            out.append(entry)
        return out

"""Step-timeline and goodput accounting: where each second of wall clock went.

On an async backend a training/serving loop has three very different kinds of
time that one `elapsed / steps` number conflates:

  - **data_wait** — the host blocked on the input pipeline (`next(loader)`);
  - **dispatch** — the host enqueued the jitted program (returns long before
    the device finishes: cheap when pipelined, a hang when the backend stalls);
  - **block** — sampled `block_until_ready` on a step's outputs, the only
    honest measure of device compute (never every step: a per-step sync
    serializes dispatch against the device, rule TPU111).

`StepTimeline` splits per-step wall clock into those phases (latency
histograms per phase, one shared log-spaced bucket layout) and keeps the
**goodput ledger**: time *lost* to overheads a production run must budget —
checkpoint saves (`Accelerator.save_state` charges them), restarts
(`fault_tolerance` downtime), and (re)compiles, either charged by duration via
the `jax.monitoring` compile-duration hook or counted from an
`analysis.TraceGuard` ledger. ``goodput()`` then answers the question the r05
postmortem could not: of the wall clock this run burned, what fraction was
productive steps, what was charged to which overhead, and how much is
unaccounted (the signature of an opaque backend hang).

All timing is host-side `perf_counter` arithmetic — the timeline never touches
device values except the explicitly-sampled `block_until_ready`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Optional

from ..logging import get_logger
from .metrics import MetricsRegistry

logger = get_logger(__name__)

#: Step phases with first-class histograms (charge() accepts any cause).
PHASES = ("data_wait", "dispatch", "block")

#: Well-known goodput loss causes (an arbitrary cause string is also accepted;
#: these are the ones the framework charges itself).
LOSS_CAUSES = ("checkpoint", "restart", "compile", "recompile")


class StepTimeline:
    """Per-step phase timing + a goodput ledger, publishing into a registry.

    Typical training wiring (what `Accelerator.train_step` instruments)::

        timeline = StepTimeline(registry, prefix="train", sample_block_every=32)
        for _ in range(steps):
            with timeline.phase("data_wait"):
                batch = next(stream)
            with timeline.phase("dispatch"):
                out = step_fn(batch)
            timeline.step_done(out)   # sampled block_until_ready on `out`
        report = timeline.goodput()

    ``sample_block_every=K`` blocks on every K-th step's outputs (K=0 never
    blocks): the sampled block time estimates the device-compute floor without
    serializing the steady-state pipeline.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        prefix: str = "step",
        sample_block_every: int = 0,
        clock: Callable[[], float] = time.perf_counter,
        tracer=None,
        unaccounted_warn_s: Optional[float] = 60.0,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.prefix = prefix
        self.sample_block_every = int(sample_block_every)
        self._clock = clock
        # The unaccounted-time alarm: `goodput()` reports `unaccounted_s` but
        # a number nobody reads is not a diagnostic. When a window's residual
        # exceeds this threshold, goodput() WARNS (once per window) and drops
        # a span event through `tracer` — the same "missing time" definition
        # the hang watchdog dumps on, so the ledger and the watchdog agree.
        self.tracer = tracer
        self.unaccounted_warn_s = unaccounted_warn_s
        self._unaccounted_warned = False
        self._lock = threading.Lock()
        self.steps = 0
        self._phase_totals: Dict[str, float] = {}
        self._productive_s = 0.0
        self._lost: Dict[str, float] = {}
        self._step_open_since: Optional[float] = None
        self._start = clock()
        self._steps_counter = self.registry.counter(
            f"{prefix}_steps_total", help="completed steps observed by the timeline"
        )
        self._step_hist = self.registry.histogram(
            f"{prefix}_step_seconds", help="wall-clock per step (all phases)"
        )
        self._phase_hists = {
            name: self.registry.histogram(
                f"{prefix}_{name}_seconds", help=f"per-step {name} wall-clock"
            )
            for name in PHASES
        }
        self._goodput_gauge = self.registry.gauge(
            f"{prefix}_goodput_ratio", help="productive step time / total wall clock"
        )
        self._monitoring_hooked = False

    # ------------------------------------------------------------------ phases
    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one phase of the current step. The first phase of a step opens
        the step; `step_done()` closes it."""
        t0 = self._clock()
        with self._lock:
            if self._step_open_since is None:
                self._step_open_since = t0
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                self._phase_totals[name] = self._phase_totals.get(name, 0.0) + dt
            hist = self._phase_hists.get(name)
            if hist is None:
                hist = self.registry.histogram(f"{self.prefix}_{name}_seconds")
                self._phase_hists[name] = hist
            hist.observe(dt)

    def record_phase(self, name: str, seconds: float):
        """Attribute already-measured wall clock to a phase WITHOUT opening a
        step — for work that runs after `step_done()` (e.g. a validation-mode
        readback): using `phase()` there would reopen the step and skew the
        next step's wall clock."""
        seconds = float(seconds)
        if seconds < 0:
            raise ValueError("cannot record negative time")
        with self._lock:
            self._phase_totals[name] = self._phase_totals.get(name, 0.0) + seconds
        hist = self._phase_hists.get(name)
        if hist is None:
            hist = self.registry.histogram(f"{self.prefix}_{name}_seconds")
            self._phase_hists[name] = hist
        hist.observe(seconds)

    def step_done(self, outputs=None) -> float:
        """Close the current step; returns its wall-clock seconds. On sampled
        steps (every `sample_block_every`-th, when `outputs` is given) blocks
        until `outputs` are ready and records the wait as the "block" phase —
        the sampled device-compute attribution."""
        with self._lock:
            opened = self._step_open_since
            self._step_open_since = None
            self.steps += 1
            sampled = (
                outputs is not None
                and self.sample_block_every > 0
                and self.steps % self.sample_block_every == 0
            )
        if sampled:
            import jax

            t0 = self._clock()
            jax.block_until_ready(outputs)
            dt = self._clock() - t0
            with self._lock:
                self._phase_totals["block"] = self._phase_totals.get("block", 0.0) + dt
            self._phase_hists["block"].observe(dt)
        now = self._clock()
        step_s = (now - opened) if opened is not None else 0.0
        with self._lock:
            self._productive_s += step_s
        self._steps_counter.inc()
        self._step_hist.observe(step_s)
        return step_s

    # ------------------------------------------------------------------ ledger
    def charge(self, cause: str, seconds: float):
        """Charge lost wall-clock to a cause (checkpoint/restart/compile/...).
        Lost time is *overhead the run paid that was not a training/serving
        step*: it lowers goodput without touching the phase histograms."""
        seconds = float(seconds)
        if seconds < 0:
            raise ValueError("cannot charge negative time")
        with self._lock:
            self._lost[cause] = self._lost.get(cause, 0.0) + seconds
        self.registry.counter(
            f"{self.prefix}_lost_seconds_total",
            help="wall-clock charged to overhead causes",
            labels={"cause": cause},
        ).inc(seconds)

    def attach_compile_listener(self):
        """Charge every backend compile's DURATION to the "compile" cause via
        the `jax.monitoring` compile-duration event (the same event
        `TraceGuard` cross-checks counts with). Warmup compiles are lost time
        too — a run that spends 10 of 30 minutes tracing has 2/3 the goodput —
        so all compiles are charged here; steady-state *re*compiles are the
        subset `observe_trace_guard` counts."""
        if self._monitoring_hooked:
            return
        import jax.monitoring

        def on_duration(event: str, duration: float, **kwargs):
            if event == "/jax/core/compile/backend_compile_duration":
                self.charge("compile", duration)

        jax.monitoring.register_event_duration_secs_listener(on_duration)
        self._monitoring_hooked = True

    def observe_trace_guard(self, guard):
        """Fold an `analysis.TraceGuard` ledger into the registry: steady-state
        recompile and guarded-transfer COUNTS become counters (the guard has no
        durations — `attach_compile_listener` carries the time side)."""
        report = guard.report()
        recompiles = self.registry.counter(
            f"{self.prefix}_recompiles_total",
            help="steady-state recompiles observed by TraceGuard",
        )
        delta = report.total_recompiles - recompiles.value
        if delta > 0:
            recompiles.inc(delta)
        transfers = self.registry.counter(
            f"{self.prefix}_guarded_transfers_total",
            help="guarded host transfers observed by TraceGuard",
        )
        delta = report.host_transfers - transfers.value
        if delta > 0:
            transfers.inc(delta)

    # ------------------------------------------------------------------ report
    def goodput(self) -> dict:
        """The accounting answer: total wall clock since construction/reset,
        productive step seconds, per-cause lost seconds, and the residual
        `unaccounted_s` (host work between steps — or an opaque stall). The
        `goodput` ratio is productive/total; `accounted` is
        (productive+lost)/total — the r05-style hang diagnostic is a LOW
        accounted fraction."""
        now = self._clock()
        with self._lock:
            total = max(now - self._start, 1e-9)
            productive = self._productive_s
            lost = dict(self._lost)
            phases = dict(self._phase_totals)
            steps = self.steps
        lost_total = sum(lost.values())
        goodput = productive / total
        self._goodput_gauge.set(goodput)
        unaccounted = max(total - productive - lost_total, 0.0)
        if (
            self.unaccounted_warn_s is not None
            and unaccounted >= self.unaccounted_warn_s
            and not self._unaccounted_warned
        ):
            # Once per accounting window: the r05-hang signature surfacing at
            # RUNTIME instead of waiting for a postmortem to read the ledger.
            self._unaccounted_warned = True
            logger.warning(
                "goodput: %.1fs of wall clock is unaccounted (total %.1fs, productive "
                "%.1fs, lost %.1fs) — the host is stalling outside the instrumented "
                "loop (backend init, a dead tunnel, or an opaque hang)",
                unaccounted, total, productive, lost_total,
            )
            if self.tracer is not None:
                self.tracer.event(
                    "goodput.unaccounted", category="goodput",
                    unaccounted_s=round(unaccounted, 3), total_s=round(total, 3),
                    productive_s=round(productive, 3), lost_s=round(lost_total, 3),
                )
        return {
            "total_s": round(total, 6),
            "steps": steps,
            "productive_s": round(productive, 6),
            "lost_s": {k: round(v, 6) for k, v in sorted(lost.items())},
            "lost_total_s": round(lost_total, 6),
            "unaccounted_s": round(unaccounted, 6),
            "phase_s": {k: round(v, 6) for k, v in sorted(phases.items())},
            "goodput": round(goodput, 6),
            "accounted": round(min((productive + lost_total) / total, 1.0), 6),
        }

    def reset(self):
        """Restart the accounting window (registry instruments keep their
        lifetime totals; the goodput ledger starts fresh)."""
        with self._lock:
            self._start = self._clock()
            self.steps = 0
            self._phase_totals = {}
            self._productive_s = 0.0
            self._lost = {}
            self._step_open_since = None
            self._unaccounted_warned = False

"""The Accelerator: the user-facing facade (L5).

TPU-native redesign of reference accelerator.py (3409 LoC). The ergonomic contract is
preserved — construct one object, `prepare()` your objects, train with
`accumulate()`/`backward()`/`step()`, evaluate with `gather_for_metrics()`, checkpoint
with `save_state()`/`load_state()` — while the machinery underneath is GSPMD:

  - `prepare(model)` derives NamedShardings from the active plugins and places params on
    the mesh (replaces the DDP/FSDP/DeepSpeed/Megatron branch tree,
    reference accelerator.py:1248-1295,1414-1886).
  - `backward(loss_fn, batch)` runs a jitted value_and_grad; gradient cross-replica
    reduction is *implicit* in the sharded-batch loss (no NCCL hooks, no `no_sync`
    machinery — the reference's `xm.all_reduce`-once-per-step trick at
    optimizer.py:140-146 becomes a compiler decision).
  - `accumulate()` keeps the reference's eager-feel contract (`_do_sync`,
    end-of-dataloader forcing, reference accelerator.py:999-1057) while each microbatch
    is one jitted call with donated accumulation buffers.

The canonical loop::

    accelerator = Accelerator(mixed_precision="bf16")
    model, optimizer, train_dl, scheduler = accelerator.prepare(model, optimizer, train_dl, scheduler)
    for batch in train_dl:
        with accelerator.accumulate(model):
            loss = accelerator.backward(model.loss, batch)
            optimizer.step()
            scheduler.step()
            optimizer.zero_grad()

where `model.loss(params, batch)` is any differentiable scalar function of the params.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
import re
import time
from typing import Any, Callable, List, Optional, Union

import numpy as np

from .checkpointing import (
    AdaptiveSaveInterval,
    AsyncCommitter,
    CheckpointCommitError,
    CheckpointManager,
    is_sharded_checkpoint_dir,
    load_accelerator_state,
    load_custom_state,
    load_sharded_accelerator_state,
    save_accelerator_state,
    save_custom_state,
    sharded_manifest_extra,
    snapshot_accelerator_state,
    write_accelerator_snapshot,
    write_checkpoint_manifest,
)
from .data_loader import DataLoaderDispatcher, DataLoaderShard, SimpleDataLoader, prepare_data_loader, skip_first_batches
from .logging import get_logger
from .modeling import Model, PreparedModel
from .optimizer import AcceleratedOptimizer, GradScaler
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .telemetry import MetricsRegistry, ProfilerManager, StepTimeline
from .telemetry.tracing import default_tracer
from .tracking import LOGGER_TYPE_TO_CLASS, GeneralTracker, filter_trackers
from .utils import operations as ops
from .utils.dataclasses import (
    AutocastKwargs,
    FP8RecipeKwargs,
    CompilationConfig,
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    KwargsHandler,
    MegatronLMPlugin,
    ParallelismConfig,
    PrecisionType,
    ProjectConfiguration,
    SequenceParallelPlugin,
)
from .utils.environment import parse_flag_from_env
from .utils.random import set_seed

logger = get_logger(__name__)


class Accelerator:
    """Creates the distributed environment and owns object preparation
    (reference accelerator.py:163)."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        parallelism_config: Optional[ParallelismConfig] = None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        deepspeed_plugin: Optional[DeepSpeedPlugin] = None,
        megatron_lm_plugin: Optional[MegatronLMPlugin] = None,
        sequence_parallel_plugin: Optional[SequenceParallelPlugin] = None,
        compilation_config: Optional[CompilationConfig] = None,
        rng_types: Optional[List[str]] = None,
        kwargs_handlers: Optional[List[KwargsHandler]] = None,
        step_scheduler_with_optimizer: bool = True,
        analyze: bool = False,
        tracer=None,
        async_save: Optional[bool] = None,
        sharded_save: Optional[bool] = None,
        save_interval: Optional[Union[int, str]] = None,
        lost_checkpoint_s: float = 300.0,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # analyze=True arms the runtime half of `accelerate analyze`: every
        # train_step() built from this Accelerator is wrapped in a TraceGuard
        # that (after a warmup allowance) raises when a steady-state step
        # recompiles or makes a guarded host transfer. See docs/analysis.md.
        self.analyze = bool(analyze)
        self.trace_guard = None
        if self.analyze:
            from .analysis import TraceGuard

            self.trace_guard = TraceGuard(name="train-step", on_violation="raise")

        if mixed_precision is not None:
            mixed_precision = str(mixed_precision)
            if mixed_precision not in PrecisionType:
                raise ValueError(f"Unknown mixed_precision mode: {mixed_precision}; choose {PrecisionType.list()}")

        # kwargs handlers (reference accelerator.py:338-375)
        self.scaler_handler = None
        self.init_handler = None
        self.autocast_handler = None
        self.ddp_handler = None
        self.fp8_recipe_handler = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                self.init_handler = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                self.ddp_handler = handler  # accepted for parity; no-op under GSPMD
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe_handler = handler

        init_kwargs = {}
        if self.init_handler is not None and self.init_handler.timeout is not None:
            init_kwargs["timeout"] = self.init_handler.timeout
        if fsdp_plugin is None and parse_flag_from_env("ACCELERATE_TPU_USE_FSDP"):
            fsdp_plugin = FullyShardedDataParallelPlugin()
        if sequence_parallel_plugin is None and os.environ.get("ACCELERATE_TPU_SP_MODE"):
            from .utils import SequenceParallelPlugin

            sequence_parallel_plugin = SequenceParallelPlugin(
                seq_degree=int(os.environ.get("ACCELERATE_TPU_MESH_SEQ", "1") or 1),
                mode=os.environ["ACCELERATE_TPU_SP_MODE"],
                block_size=int(os.environ.get("ACCELERATE_TPU_SP_BLOCK_SIZE", "512")),
            )

        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            parallelism_config=parallelism_config,
            fsdp_plugin=fsdp_plugin,
            deepspeed_plugin=deepspeed_plugin,
            megatron_lm_plugin=megatron_lm_plugin,
            sequence_parallel_plugin=sequence_parallel_plugin,
            _from_accelerator=True,
            **init_kwargs,
        )

        if gradient_accumulation_plugin is None:
            gas = int(os.environ.get("ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS", gradient_accumulation_steps))
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=gas)
        self.gradient_state = GradientState(gradient_accumulation_plugin=gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.split_batches = split_batches
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(split_batches=split_batches)
        self.compilation_config = compilation_config or CompilationConfig()
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["python", "numpy"]

        self.scaler = None
        if self.state.mixed_precision == "fp16":
            self.scaler = GradScaler(self.scaler_handler)

        # trackers
        self.log_with = filter_trackers(log_with, self.logging_dir)
        self.trackers: List[GeneralTracker] = []

        # prepared-object registries (reference accelerator.py keeps _models/_optimizers/...)
        self._models: List[PreparedModel] = []
        self._optimizers: List[AcceleratedOptimizer] = []
        self._schedulers: List[AcceleratedScheduler] = []
        self._dataloaders: List[Any] = []
        self._custom_objects: List[Any] = []
        self._backward_cache: dict = {}
        self._save_model_hooks: List[Callable] = []
        self._load_model_hooks: List[Callable] = []
        # Global batch observed on a co-prepared dataloader (prepare() peeks
        # before placing models): sizes the MPMD microbatch schedule.
        self._planning_batch_hint: Optional[int] = None

        self.step = 0
        self.flag_tensor = None

        # Telemetry (the observability pillar, docs/observability.md): one
        # registry for this Accelerator's instruments, a StepTimeline splitting
        # per-step wall clock + keeping the goodput ledger, and a
        # ProfilerManager armed from the launch env protocol
        # (ACCELERATE_TPU_PROFILE_DIR, set by `launch --profile_dir`) for
        # touch-file / SIGUSR2 on-demand capture. All construction is host-only
        # and free when profiling wasn't requested.
        self.telemetry = MetricsRegistry()
        # Request-scoped tracing + the crash/hang flight recorder: the tracer
        # comes from the launch env protocol (ACCELERATE_TPU_TRACE_DIR/_ID/
        # _PARENT, set by `launch --trace_dir` and the Supervisor) unless the
        # caller hands one in. When a trace dir is armed, exit/SIGTERM dumps,
        # the compile-event listener, and the hang watchdog
        # (ACCELERATE_TPU_HANG_DEADLINE_S, default 300 s without a step
        # heartbeat) arm with it — the next r05-style stall dumps its own
        # timeline and thread stacks instead of dying silent.
        self.tracer = tracer if tracer is not None else default_tracer()
        self.hang_watchdog = None
        recorder = getattr(self.tracer, "recorder", None)
        if recorder is not None and getattr(recorder, "log_dir", None):
            recorder.install_exit_hooks()
            self.tracer.attach_compile_listener()
            deadline = float(os.environ.get("ACCELERATE_TPU_HANG_DEADLINE_S", "300") or 0)
            if deadline > 0:
                self.hang_watchdog = recorder.start_watchdog(
                    deadline_s=deadline, tracer=self.tracer
                )
        self.timeline = StepTimeline(self.telemetry, prefix="train", tracer=self.tracer)
        self.profiler = ProfilerManager.from_env(registry=self.telemetry)
        self._m_ckpt_saves = self.telemetry.counter(
            "checkpoint_saves_total", help="save_state() completions"
        )
        self._m_ckpt_seconds = self.telemetry.histogram(
            "checkpoint_save_seconds", help="wall-clock per save_state()"
        )
        self._m_ckpt_loads = self.telemetry.counter(
            "checkpoint_loads_total", help="load_state() completions (restart recoveries)"
        )

        # Async/sharded checkpointing (docs/guides/checkpointing.md): with
        # `async_save` the train loop only pays for the device->host snapshot
        # (and a barrier on the PREVIOUS commit when it is still in flight);
        # serialize+fsync+publish run on a background committer whose time is
        # `checkpoint_async_commit_seconds`, not goodput-lost step time. With
        # `sharded_save` each process writes only its addressable shards into a
        # per-host subdirectory. Defaults ride the launch env protocol
        # (`launch --async_save` / `--sharded_save`).
        if async_save is None:
            async_save = parse_flag_from_env("ACCELERATE_TPU_ASYNC_SAVE")
        if sharded_save is None:
            sharded_save = parse_flag_from_env("ACCELERATE_TPU_SHARDED_SAVE")
        self.async_save = bool(async_save)
        self.sharded_save = bool(sharded_save)
        self._async_committer: Optional[AsyncCommitter] = None
        # Checkpoint cadence (ROADMAP 4b): `save_interval="auto"` derives the
        # save interval from the goodput ledger's measured blocking save cost
        # against the `lost_checkpoint_s` budget (work a crash may lose); an
        # int is the classic fixed every-N-steps cadence. Either arms
        # `maybe_save_state()` as the step-boundary driver.
        self.save_controller: Optional[AdaptiveSaveInterval] = None
        if save_interval == "auto":
            self.save_controller = AdaptiveSaveInterval(lost_checkpoint_s=lost_checkpoint_s)
        elif save_interval is not None:
            self.save_controller = AdaptiveSaveInterval(
                lost_checkpoint_s=lost_checkpoint_s, fixed_interval=int(save_interval)
            )
        self._steps_since_save = 0
        self._last_step_boundary: Optional[float] = None
        self._m_ckpt_commit_seconds = self.telemetry.histogram(
            "checkpoint_async_commit_seconds",
            help="background (async) checkpoint commit wall-clock — overlapped "
            "with training, NOT charged to the goodput ledger",
        )
        self._g_ckpt_in_flight = self.telemetry.gauge(
            "checkpoint_commits_in_flight", help="async checkpoint commits currently running"
        )

        if self.compilation_config.cache_dir:
            import jax

            jax.config.update("jax_compilation_cache_dir", self.compilation_config.cache_dir)

    # ------------------------------------------------------------------ state passthrough
    @property
    def distributed_type(self) -> DistributedType:
        return self.state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    def __repr__(self):
        return repr(self.state._partial) + f"Mixed precision: {self.mixed_precision}\n"

    # ------------------------------------------------------------------ process control
    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def print(self, *args, **kwargs):
        self.state._partial.print(*args, **kwargs)

    def on_main_process(self, function):
        return self.state._partial.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state._partial.on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return self.state._partial.on_process(function, process_index)

    @contextlib.contextmanager
    def main_process_first(self):
        with self.state._partial.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.state._partial.local_main_process_first():
            yield

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state._partial.split_between_processes(inputs, apply_padding=apply_padding)

    # ------------------------------------------------------------------ accumulation
    def _do_sync(self):
        """Decide whether this step is a sync boundary (reference accelerator.py:999)."""
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients((self.step % self.gradient_state.num_steps) == 0)

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Gradient-accumulation context (reference accelerator.py:1024-1058).

        Under GSPMD there is no DDP `no_sync` to enter — skipping the cross-replica
        reduction while accumulating falls out of *not applying* the optimizer update;
        per-microbatch grads stay resident as sharded device arrays.
        """
        self._do_sync()
        yield

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Parity shim (reference accelerator.py:909-948): forces the next `step()` to
        skip; gradient reduction cost is already deferred under GSPMD."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """Parity shim for torch's DDP Join (reference accelerator.py:1060-1131): under
        jit-stable shapes + even_batches padding there are no uneven inputs to join."""
        if even_batches is not None:
            logger.warning("join_uneven_inputs(even_batches=...) is advisory here; padding is handled by the loader")
        yield

    # ------------------------------------------------------------------ prepare
    def prepare(self, *args, device_placement=None):
        """Prepare models/optimizers/dataloaders/schedulers in one call
        (reference accelerator.py:1180). Order-independent; schedulers bind to the
        prepared optimizers in a second pass (reference two-pass at :1163)."""
        if device_placement is None:
            device_placement = [None] * len(args)
        elif not isinstance(device_placement, (list, tuple)):
            device_placement = [device_placement] * len(args)

        # Peek at co-prepared dataloaders BEFORE placing models: the MPMD
        # pipeline planner sizes its microbatch schedule off the global batch,
        # and a schedule planned for the wrong batch fails loudly at step time
        # (mpmd.py's split guard) instead of training on wrong gradients.
        for obj in args:
            if self._is_dataloader(obj):
                bs = (
                    getattr(obj, "total_batch_size", None)
                    or getattr(obj, "batch_size", None)
                    or getattr(getattr(obj, "batch_sampler", None), "batch_size", None)
                )
                if bs:
                    self._planning_batch_hint = int(bs)
                    break

        first_pass = []
        for obj, dp in zip(args, device_placement):
            if self._is_model(obj):
                first_pass.append(self.prepare_model(obj))
            elif self._is_optimizer(obj):
                first_pass.append(obj)  # bound after models exist
            elif self._is_dataloader(obj):
                first_pass.append(self.prepare_data_loader(obj, device_placement=dp))
            else:
                first_pass.append(obj)

        result = []
        for obj in first_pass:
            if self._is_optimizer(obj):
                result.append(self.prepare_optimizer(obj))
            else:
                result.append(obj)

        final = []
        for obj in result:
            if self._is_scheduler(obj):
                final.append(self.prepare_scheduler(obj))
            else:
                final.append(obj)
        return final[0] if len(final) == 1 else tuple(final)

    @staticmethod
    def _is_model(obj) -> bool:
        from .parallel.mpmd import MPMDPipelinedModel
        from .parallel.pipeline import PipelinedModel

        return isinstance(obj, (Model, PreparedModel, PipelinedModel, MPMDPipelinedModel))

    @staticmethod
    def _is_optimizer(obj) -> bool:
        if isinstance(obj, AcceleratedOptimizer):
            return True
        return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")

    @staticmethod
    def _is_dataloader(obj) -> bool:
        if isinstance(obj, (DataLoaderShard, DataLoaderDispatcher, SimpleDataLoader)):
            return True
        from .native.loader import NativeArrayLoader

        if isinstance(obj, NativeArrayLoader):
            return True
        try:
            import torch.utils.data

            if isinstance(obj, torch.utils.data.DataLoader):
                return True
        except ImportError:
            pass
        return False

    @classmethod
    def _is_scheduler(cls, obj) -> bool:
        if isinstance(obj, AcceleratedScheduler):
            return True
        if cls._is_model(obj) or cls._is_optimizer(obj) or cls._is_dataloader(obj):
            return False
        # optax schedules are bare callables step->lr; or any object with get_last_lr()
        return (callable(obj) and not isinstance(obj, type) and not hasattr(obj, "init")) or hasattr(
            obj, "get_last_lr"
        )

    def prepare_model(self, model: Union[Model, PreparedModel], device_placement=None, evaluation_mode=False):
        """Place a model on the mesh with derived shardings
        (reference prepare_model accelerator.py:1316)."""
        from .parallel.mpmd import MPMDPipelinedModel
        from .parallel.pipeline import PipelinedModel

        if isinstance(model, (PreparedModel, PipelinedModel, MPMDPipelinedModel)):
            # Already placed (pipeline models are stage-sharded at construction).
            if model not in self._models:
                self._models.append(model)
            return model
        from .parallel.sharding import derive_param_shardings

        mesh = self.mesh
        fsdp = self.state.fsdp_plugin
        if (
            fsdp is not None
            and fsdp.sync_module_states
            and self.num_processes > 1
            and not evaluation_mode
        ):
            # Reference FSDP sync_module_states (accelerator.py:1431+): rank 0's
            # initial weights win, so per-host random init or racy loads can't
            # diverge the replicas. Runs on host arrays before placement.
            from .utils.operations import broadcast

            model.params = broadcast(model.params, from_process=0)
        if isinstance(model.sharding_rules, str):
            # sharding_rules="auto": the cost-model planner searches the
            # MODEL-axis (tensor-parallel) layout for this mesh and emits the
            # rules table every consumer below (param/opt-state derivation)
            # already reads. The planner owns only the "model" axis here:
            # "fsdp" sharding stays the deriver's job — the fsdp_plugin is
            # the user's explicit memory request, spec_for_param extends the
            # planner's rules with the fsdp dim exactly as it extends the
            # hand tables (Megatron+ZeRO composition), and overriding that
            # from a cost model that can't see the real batch would silently
            # undo a policy the user set on purpose. The resolved table
            # replaces the sentinel on the bundle so the optimizer's mirrored
            # derivation sees the same rules, not the string.
            from .parallel.planner import Workload, resolve_sharding_rules

            if model.sharding_rules == "rules":
                raise ValueError(
                    "sharding_rules='rules' is a serving-engine sentinel (it "
                    "means 'fall back to the Model bundle's family table'); on "
                    "this seam the bundle's sharding_rules IS that table, and "
                    "the sentinel just overwrote it — leave the table in place, "
                    "or pass 'auto' for the planner"
                )
            adam_bytes = 8.0  # fp32 moments; the dominant non-param account
            # Training meshes add the "data" axis to the search: the planner
            # then enumerates ZeRO twins (optimizer moments sharded along
            # "data" even where params replicate) and emits them as a second
            # rules table the optimizer derivation consumes.
            mesh_sizes = dict(getattr(mesh, "shape", {}) or {})
            if mesh_sizes.get("pipeline", 1) > 1:
                # 3-axis mesh: plan-and-place the MPMD pipeline executor. The
                # planner byte-balances the layers onto the "pipeline" axis
                # (assignments may be NON-uniform), emits a full 2D rules +
                # ZeRO opt-rules pair PER STAGE submesh, and the runtime
                # places each stage by its own tables — the prepared object
                # is an MPMDPipelinedModel whose step comes from
                # `Accelerator.train_step`, not a single-mesh PreparedModel.
                from .models import layered_for_model
                from .parallel.planner import plan_mpmd_train_sharding

                # Settings the single-mesh route honors must not be dropped
                # silently here (same explicit-rejection style as train_step's
                # loss_fn/max_grad_norm): ZeRO weight-update sharding already
                # rides the per-stage opt-rules tables, but the fsdp param/
                # grad knobs and the fp8 recipe have no per-stage twin yet.
                if fsdp is not None:
                    raise NotImplementedError(
                        "fsdp_plugin is not supported on the MPMD pipeline "
                        "route: stage params shard by the per-stage planner "
                        "tables, not the fsdp wrap policy. Drop the plugin "
                        "(ZeRO optimizer-state sharding is planned per stage "
                        "automatically) or use a 2-axis mesh."
                    )
                if self.state.mixed_precision == "fp8":
                    raise NotImplementedError(
                        "mixed_precision='fp8' is not supported on the MPMD "
                        "pipeline route (no per-stage fp8 recipe); use 'bf16' "
                        "or a 2-axis mesh."
                    )
                mp_dtype = None
                if self.state.mixed_precision in ("bf16", "fp16"):
                    mp_dtype = self.state.compute_dtype
                mp_autocast = True
                if self.autocast_handler is not None and not self.autocast_handler.enabled:
                    mp_autocast = False
                # Size the microbatch schedule off the real global batch when a
                # dataloader was prepared in the same call — a schedule divided
                # for the wrong batch can't split the step (mpmd.py raises).
                plan_batch = self._planning_batch_hint or 8
                layered = layered_for_model(model)
                prelude, layers, tail = layered.split(model.params)
                mpmd_plan = plan_mpmd_train_sharding(
                    prelude,
                    layers,
                    tail,
                    mesh,
                    batch=plan_batch,
                    seq=512,
                    opt_bytes_per_param=adam_bytes,
                )
                pipelined = MPMDPipelinedModel(
                    model,
                    layered,
                    mesh,
                    mpmd_plan,
                    compute_dtype=mp_dtype,
                    autocast=mp_autocast,
                )
                self._models.append(pipelined)
                return pipelined
            plan_axes = tuple(
                a for a in ("data", "model") if mesh_sizes.get(a, 1) > 1
            ) or ("model",)
            rules, _plan = resolve_sharding_rules(
                model.sharding_rules,
                model.params,
                mesh,
                plan_kwargs=dict(
                    axes=plan_axes,
                    workload=Workload(batch=8, seq=512, opt_bytes_per_param=adam_bytes),
                ),
            )
            model.sharding_rules = rules
            if _plan is not None and getattr(_plan, "opt_rules", None):
                model.opt_sharding_rules = list(_plan.opt_rules)
        param_sharding = derive_param_shardings(
            model.params, mesh, fsdp_plugin=fsdp, rules=model.sharding_rules
        )
        compute_dtype = None
        autocast = True
        if self.autocast_handler is not None and not self.autocast_handler.enabled:
            autocast = False
        if self.state.mixed_precision in ("bf16", "fp16", "fp8"):
            compute_dtype = self.state.compute_dtype
        fp8_recipe = None
        if self.state.mixed_precision == "fp8":
            fp8_recipe = self.fp8_recipe_handler or FP8RecipeKwargs()
        # Activation checkpointing: the CompilationConfig policy (expert knob)
        # wins; the FSDP boolean maps to classic full per-layer remat.
        remat_policy = self.compilation_config.remat_policy
        if remat_policy is None and fsdp is not None and fsdp.activation_checkpointing:
            remat_policy = "full"
        prepared = PreparedModel(
            model,
            mesh=mesh,
            param_sharding=param_sharding,
            compute_dtype=compute_dtype,
            autocast=autocast,
            fp8_recipe=fp8_recipe,
            offload_params=bool(getattr(fsdp, "offload_params", False)),
            param_dtype=getattr(fsdp, "param_dtype", None),
            reduce_dtype=getattr(fsdp, "reduce_dtype", None),
            remat_policy=remat_policy,
        )
        self._models.append(prepared)
        return prepared

    def prepare_optimizer(self, optimizer, device_placement=None, model=None) -> AcceleratedOptimizer:
        """Bind an optax transformation to the (single) prepared model
        (reference prepare_optimizer accelerator.py:2011)."""
        if isinstance(optimizer, AcceleratedOptimizer):
            if optimizer not in self._optimizers:
                self._optimizers.append(optimizer)
            return optimizer
        if model is None:
            if len(self._models) == 0:
                raise ValueError(
                    "Prepare the model before (or together with) the optimizer: the optimizer "
                    "state is sharded like the parameters it updates."
                )
            model = self._models[-1]
        prepared = AcceleratedOptimizer(
            optimizer,
            model=model,
            scaler=self.scaler,
            mesh=self.mesh,
            fsdp_plugin=self.state.fsdp_plugin,
        )
        self._optimizers.append(prepared)
        return prepared

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        """(reference prepare_data_loader accelerator.py:1958)"""
        if isinstance(data_loader, (DataLoaderShard, DataLoaderDispatcher)):
            if data_loader not in self._dataloaders:
                self._dataloaders.append(data_loader)
            return data_loader
        if device_placement is None:
            device_placement = self.device_placement
        cfg = self.dataloader_config
        prepared = prepare_data_loader(
            data_loader,
            split_batches=cfg.split_batches or self.split_batches,
            put_on_device=device_placement,
            rng_types=self.rng_types.copy(),
            dispatch_batches=cfg.dispatch_batches,
            even_batches=cfg.even_batches,
            slice_fn_for_dispatch=slice_fn_for_dispatch,
            use_seedable_sampler=cfg.use_seedable_sampler,
            prefetch_size=cfg.prefetch_size,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        """(reference prepare_scheduler accelerator.py:2052)"""
        if isinstance(scheduler, AcceleratedScheduler):
            if scheduler not in self._schedulers:
                self._schedulers.append(scheduler)
            return scheduler
        prepared = AcceleratedScheduler(
            scheduler,
            self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches or self.split_batches,
        )
        self._schedulers.append(prepared)
        # Order-independent with train_step(steps_per_call=K): whichever comes
        # second surfaces the coarsening.
        k = getattr(self, "_last_steps_per_call", 1)
        if k > 1:
            self._warn_scheduler_coarsened(k)
        return prepared

    def _warn_scheduler_coarsened(self, steps_per_call: int):
        """A scheduler's contract is one LR update per optimizer step; the
        scanned device loop reads the LR override ONCE per compiled call, so
        K>1 coarsens the schedule to K-step strides (documented in
        train_step.py's docstring; this surfaces it at prepare time instead of
        leaving it to be discovered from a training curve)."""
        logger.warning(
            "train_step(steps_per_call=%d) with a prepared scheduler: the LR is "
            "read once per compiled call, so the scheduler advances in %d-step "
            "strides instead of per optimizer step. Use steps_per_call=1 for an "
            "exact per-step schedule, or step the scheduler once per call.",
            steps_per_call,
            steps_per_call,
        )

    # ------------------------------------------------------------------ backward
    def _resolve_model(self, model) -> PreparedModel:
        if model is not None:
            return model
        if len(self._models) == 1:
            return self._models[0]
        raise ValueError("Multiple prepared models: pass model= to backward()/clip_grad_norm_().")

    def _optimizer_for(self, model: PreparedModel) -> AcceleratedOptimizer:
        for opt in self._optimizers:
            if opt.model is model:
                return opt
        raise ValueError("No prepared optimizer bound to this model.")

    def backward(self, loss_fn: Callable, *args, model: Optional[PreparedModel] = None, **kwargs):
        """Compute gradients of `loss_fn(params, *args, **kwargs)` and accumulate them
        into the bound optimizer; returns the (unscaled, fp32) loss value.

        The reference divides the loss by the accumulation count (accelerator.py:2115)
        and lets autograd run — here the same scaling happens inside one jitted
        value_and_grad whose gradient pytree inherits the parameter shardings, so the
        reduce-scatter/psum over ("data","fsdp") is fused into the backward by XLA.
        """
        model = self._resolve_model(model)
        if getattr(model, "is_mpmd", False):
            raise NotImplementedError(
                "backward() computes one single-mesh grad pytree; an MPMD "
                "pipeline model's gradients live per stage on per-stage "
                "submeshes. Use step_fn = accelerator.train_step() — it runs "
                "the 1F1B schedule with per-stage accumulation and updates."
            )
        optimizer = self._optimizer_for(model)
        # Key on the underlying function object (held strongly by the dict), not id():
        # bound methods like `model.loss` are re-created per access (id churn → retrace),
        # and a freed function's id can be reused (silent stale-closure hit).
        key = (getattr(loss_fn, "__func__", loss_fn), id(model))
        if key not in self._backward_cache:
            import jax

            # Optional PreparedModel protocol, same guard as optimizer.py:289 /
            # train_step.py:105 — duck-typed models need not implement offload.
            to_compute = getattr(model, "to_compute_memory", lambda p: p)

            def _compute(params, scale, *fargs, **fkwargs):
                # Host-offloaded params stream to device memory OUTSIDE the grad
                # closure so gradients come out device-resident.
                params = to_compute(params)

                def scaled(p):
                    out = loss_fn(p, *fargs, **fkwargs)
                    loss, aux = out if isinstance(out, tuple) else (out, None)
                    return loss * scale, (loss, aux)

                grads, (loss, aux) = jax.grad(scaled, has_aux=True)(params)
                return grads, loss, aux

            self._backward_cache[key] = jax.jit(_compute)
        import jax.numpy as jnp

        scale = 1.0 / self.gradient_state.num_steps
        if self.scaler is not None and self.scaler.enabled:
            scale = scale * self.scaler.scale
        grads, loss, aux = self._backward_cache[key](model.params, jnp.asarray(scale, jnp.float32), *args, **kwargs)
        optimizer.accumulate_grads(grads)
        if aux is not None:
            return loss, aux
        return loss

    def train_step(
        self,
        loss_fn: Optional[Callable] = None,
        *,
        model: Optional[PreparedModel] = None,
        max_grad_norm: Optional[float] = None,
        accumulation_steps: Optional[int] = None,
        steps_per_call: int = 1,
    ):
        """Build the fused per-step program: ONE jitted call doing
        value_and_grad + (clip) + optimizer update with donated params/opt-state,
        with `lax.scan` microbatch accumulation when `accumulation_steps > 1`.

        `steps_per_call=K > 1` additionally scans K FULL optimizer steps inside
        the one program (pass a batch stacking K step-batches along dim 0); host
        dispatch cost is paid once per K steps — the device-training-loop mode
        for small-step configs and high-latency (tunneled) hosts.

        This is the TPU performance path; `backward()`/`optimizer.step()` remain as
        the eager-feel compatibility surface (reference accelerator.py:2093-2121).

        Usage::

            step_fn = accelerator.train_step(max_grad_norm=1.0)
            for batch in loader:
                loss = step_fn(batch)
                scheduler.step()

        `accumulation_steps` defaults to the Accelerator's
        `gradient_accumulation_steps`; in that mode pass one batch pytree whose
        arrays stack the microbatches along dim 0 (`[k*b, ...]`).
        """
        from .train_step import FusedTrainStep

        model = self._resolve_model(model)
        optimizer = self._optimizer_for(model)
        if getattr(model, "is_mpmd", False):
            # MPMD pipeline route: the model already owns its per-stage
            # programs and optimizer states; the step IS the 1F1B schedule
            # (microbatch accumulation is built in — accumulation_steps and
            # loss_fn/max_grad_norm knobs belong to the single-mesh fused
            # step and are rejected rather than silently ignored).
            if loss_fn is not None or max_grad_norm is not None or steps_per_call != 1:
                raise NotImplementedError(
                    "MPMD pipeline training uses the model's logits-level loss "
                    "and per-stage updates; loss_fn=, max_grad_norm= and "
                    "steps_per_call= are not supported on this route."
                )
            step = model.make_train_step(optimizer.tx)
            if self.trace_guard is not None:
                step = self.trace_guard.wrap(step, warmup=2)
            return self._instrument_step(step)
        if accumulation_steps is None:
            accumulation_steps = self.gradient_state.num_steps
        # Latest build wins (not a ratchet): rebuilding with K=1 after a K>1
        # experiment must not leave a stale warning armed for a scheduler
        # prepared later.
        self._last_steps_per_call = steps_per_call
        if steps_per_call > 1 and self._schedulers:
            self._warn_scheduler_coarsened(steps_per_call)
        step = FusedTrainStep(
            model,
            optimizer,
            loss_fn=loss_fn,
            max_grad_norm=max_grad_norm,
            accumulation_steps=accumulation_steps,
            gradient_state=self.gradient_state,
            steps_per_call=steps_per_call,
            tracer=self.tracer,
        )
        if self.trace_guard is not None:
            # analyze mode: steady-state steps must neither recompile nor make
            # guarded host transfers. warmup=2 because the first scheduler step
            # installing an lr override legitimately rebuilds the with_lr
            # program once (train_step.py's _jitted cache).
            step = self.trace_guard.wrap(step, warmup=2)
        return self._instrument_step(step)

    def _instrument_step(self, step_fn: Callable) -> Callable:
        """Telemetry shim around the fused step: each call is timed as the
        timeline's "dispatch" phase (host enqueue — pure perf_counter
        arithmetic, no device sync), wrapped in a `train.step` span, heartbeats
        the hang watchdog, and polls the ProfilerManager + flight recorder so
        touch-file / SIGUSR2 capture and trace-dump requests are served at
        step boundaries. Exceptions (including TraceGuardViolation from
        analyze mode) propagate untouched."""
        timeline, profiler = self.timeline, self.profiler
        tracer, recorder = self.tracer, self.tracer.recorder
        counter = {"step": 0}

        def instrumented(*args, **kwargs):
            counter["step"] += 1
            with timeline.phase("dispatch"), tracer.span(
                "train.step", category="train", step=counter["step"]
            ):
                out = step_fn(*args, **kwargs)
            timeline.step_done(out)
            recorder.heartbeat()
            profiler.poll()
            recorder.poll()
            return out

        instrumented.__wrapped__ = step_fn  # type: ignore[attr-defined]
        guard = getattr(step_fn, "trace_guard", None)
        if guard is not None:
            instrumented.trace_guard = guard  # type: ignore[attr-defined]
        return instrumented

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: int = 2, model=None):
        """Clip accumulated grads by global norm; no-op while accumulating
        (reference accelerator.py:2221)."""
        if not self.sync_gradients:
            return None
        if norm_type != 2:
            raise NotImplementedError("Only the L2 global norm is supported")
        model = self._resolve_model(model)
        return self._optimizer_for(model).clip_grad_norm_(max_norm)

    def clip_grad_value_(self, parameters=None, clip_value: float = 1.0, model=None):
        if not self.sync_gradients:
            return
        model = self._resolve_model(model)
        self._optimizer_for(model).clip_grad_value_(clip_value)

    # ------------------------------------------------------------------ collectives
    def gather(self, tensor):
        """(reference accelerator.py:2299)"""
        return ops.gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather with duplicate-tail truncation on the final batch
        (reference accelerator.py:2331-2396)."""
        try:
            all_tensors = all(ops.is_array_like(t) for t in (
                input_data.values() if isinstance(input_data, dict) else
                (input_data if isinstance(input_data, (list, tuple)) else [input_data])
            ))
        except TypeError:
            all_tensors = False
        if use_gather_object or not all_tensors:
            data = ops.gather_object(input_data if isinstance(input_data, list) else [input_data])
        else:
            data = ops.gather(input_data)

        if self.gradient_state.end_of_dataloader:
            remainder = self.gradient_state.remainder
            if remainder is not None and remainder > 0:
                if use_gather_object or not all_tensors:
                    return data[:remainder]

                def _truncate(t):
                    return t[:remainder]

                return ops.recursively_apply(_truncate, data)
        return data

    def reduce(self, tensor, reduction="sum", scale=1.0):
        return ops.reduce(tensor, reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor, dim=0, pad_index=0, pad_first=False):
        return ops.pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    # ------------------------------------------------------------------ trigger
    def set_trigger(self):
        """Set a cross-process breakpoint flag (reference accelerator.py:2127)."""
        self.flag_tensor = np.array([1], dtype=np.int64)

    def check_trigger(self) -> bool:
        """True if any process called set_trigger (reference accelerator.py:2153)."""
        flag = self.flag_tensor if self.flag_tensor is not None else np.array([0], dtype=np.int64)
        total = ops.reduce(flag, reduction="sum")
        if int(np.asarray(total)[0]) >= 1:
            self.flag_tensor = None
            return True
        return False

    # ------------------------------------------------------------------ preemption
    def register_preemption_checkpoint(self, output_dir: Optional[str] = None, exit_on_save: bool = True):
        """Install a SIGTERM latch (TPU-VM preemption); `check_preemption()` then
        saves full state at the next step boundary (SURVEY §5: the elastic/preemption
        machinery the reference delegates to torchrun).

        `output_dir` is a `CheckpointManager` BASE directory: each preemption save
        commits an atomically-published `checkpoint_N` inside it, so a hard kill
        racing the save can never leave a torn checkpoint, and resume via
        `load_state(output_dir)` (or `"latest"` under automatic naming) lands on
        the newest checkpoint that digest-verifies. Off the main thread the latch
        degrades to a warn + no-op (the `signal` module's restriction) instead of
        crashing the caller."""
        from .fault_tolerance import PreemptionHandler

        self._preemption_handler = PreemptionHandler()
        self._preemption_dir = output_dir
        self._preemption_exit = exit_on_save
        return self._preemption_handler

    @property
    def preemption_requested(self) -> bool:
        handler = getattr(self, "_preemption_handler", None)
        return handler is not None and handler.preemption_requested

    def check_preemption(self) -> bool:
        """Call at step boundaries: on a latched SIGTERM, saves state (to the
        registered dir or the project checkpoint dir) and exits 143. Returns False
        when training should continue."""
        if not self.preemption_requested:
            return False
        from .fault_tolerance import PREEMPTED_EXIT_CODE

        # Flush the in-flight async commit BEFORE the preemption save: the
        # handoff must not leave a background commit racing process exit. A
        # commit that FAILED is logged, not raised — the preemption checkpoint
        # about to be written supersedes it.
        try:
            self.drain_checkpoints()
        except CheckpointCommitError as exc:
            logger.warning(
                "in-flight async checkpoint commit failed during preemption flush "
                "(%s); the preemption checkpoint will supersede it", exc,
            )
        preemption_dir = getattr(self, "_preemption_dir", None)
        if preemption_dir is not None and not self.project_configuration.automatic_checkpoint_naming:
            # The registered dir is a manager base: numbered, rotated, atomically
            # committed — the supervisor can SIGKILL us mid-save and the previous
            # checkpoint stays loadable.
            manager = CheckpointManager(preemption_dir, keep_last_n=2)
            path = manager.save(
                manager.next_step(),
                lambda staging: self._write_state_artifacts(staging, None, self.sharded_save),
                is_main=self.is_main_process,
                barrier=self.wait_for_everyone,
                manifest_extra=sharded_manifest_extra(self.num_processes)
                if self.sharded_save
                else None,
            )
        else:
            # ALWAYS synchronous: the process exits right after this save, and
            # an async commit would race its own death.
            path = self.save_state(preemption_dir, async_save=False)
        self.print(f"preemption checkpoint saved to {path}")
        if getattr(self, "_preemption_exit", True):
            raise SystemExit(PREEMPTED_EXIT_CODE)
        return True

    # ------------------------------------------------------------------ profiling
    @contextlib.contextmanager
    def profile(self, log_dir: Optional[str] = None):
        """Capture an XLA device trace for the wrapped block, via the
        `telemetry.ProfilerManager` (which also serves on-demand touch-file /
        SIGUSR2 captures between these scoped ones — docs/observability.md).
        Output is an xplane dump viewable in TensorBoard / xprof / Perfetto."""
        manager = self.profiler
        if log_dir is not None or not manager.enabled:
            if log_dir is None:
                base = self.logging_dir or self.project_dir or "."
                log_dir = os.path.join(str(base), "profile")
            # Scoped capture outside the launch-configured dir: a transient
            # manager sharing this Accelerator's registry (instruments are
            # get-or-create, so capture counts keep accumulating in one place).
            manager = ProfilerManager(log_dir=str(log_dir), registry=self.telemetry)
        with manager.trace():
            yield
        self.wait_for_everyone()

    def save_memory_profile(self, path: str):
        """Dump a device-memory (HBM) profile in pprof format."""
        if self.is_main_process:
            manager = self.profiler if self.profiler.enabled else ProfilerManager(
                log_dir=os.path.dirname(os.path.abspath(path)) or ".", registry=self.telemetry
            )
            manager.save_memory_snapshot(path)

    # ------------------------------------------------------------------ precision
    @contextlib.contextmanager
    def autocast(self, autocast_handler: Optional[AutocastKwargs] = None):
        """Toggle the compute-dtype policy for forwards inside the context
        (reference accelerator.py:3292). Jit caches are cleared on toggle."""
        handler = autocast_handler or AutocastKwargs()
        previous = [(m, m.autocast_enabled) for m in self._models]
        for m in self._models:
            if m.autocast_enabled != handler.enabled and m.compute_dtype is not None:
                m.autocast_enabled = handler.enabled
                m._jit_cache.pop("apply", None)
        try:
            yield
        finally:
            for m, prev in previous:
                if m.autocast_enabled != prev:
                    m.autocast_enabled = prev
                    m._jit_cache.pop("apply", None)

    # ------------------------------------------------------------------ model access
    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        """(reference accelerator.py:2598 → utils extract_model_from_parallel)"""
        from .utils.other import extract_model_from_parallel

        return extract_model_from_parallel(model, keep_fp32_wrapper)

    def free_memory(self, *objects):
        """Release prepared objects + compiled executables (reference accelerator.py:3128)."""
        import gc

        import jax

        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._backward_cache.clear()
        self._last_steps_per_call = 1
        self.step = 0
        objects = list(objects)
        for i in range(len(objects)):
            objects[i] = None
        gc.collect()
        jax.clear_caches()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    # ------------------------------------------------------------------ trackers
    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = None):
        """(reference accelerator.py:2611)"""
        init_kwargs = init_kwargs or {}
        self.trackers = []
        for tracker in self.log_with:
            if isinstance(tracker, GeneralTracker):
                self.trackers.append(tracker)
                continue
            tracker_cls = LOGGER_TYPE_TO_CLASS[str(tracker)]
            kwargs = init_kwargs.get(str(tracker), {})
            if tracker_cls.requires_logging_directory:
                self.trackers.append(tracker_cls(project_name, self.logging_dir, **kwargs))
            else:
                self.trackers.append(tracker_cls(project_name, **kwargs))
        if config is not None:
            for tracker in self.trackers:
                tracker.store_init_configuration(config)

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"No tracker named {name} is running")

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = None):
        """Fan out metrics to every tracker (reference accelerator.py:2639)."""
        log_kwargs = log_kwargs or {}
        for tracker in self.trackers:
            tracker.log(values, step=step, **log_kwargs.get(tracker.name, {}))

    def end_training(self):
        """(reference accelerator.py:2678). Also the shutdown barrier for async
        checkpointing: the last async commit must land (or surface its failure)
        before the run is declared over."""
        self.drain_checkpoints()
        for tracker in self.trackers:
            tracker.finish()
        self.wait_for_everyone()

    # ------------------------------------------------------------------ checkpoint
    def register_for_checkpointing(self, *objects):
        """Track extra objects in save_state/load_state (reference accelerator.py:3256)."""
        invalid = [obj for obj in objects if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"Objects must expose state_dict/load_state_dict; got invalid: {[type(o).__name__ for o in invalid]}"
            )
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook: Callable):
        self._save_model_hooks.append(hook)

    def register_load_state_pre_hook(self, hook: Callable):
        self._load_model_hooks.append(hook)

    def checkpoint_manager(self, base_dir: Optional[str] = None) -> CheckpointManager:
        """The crash-safe checkpoint store for this run: rooted at the project's
        `checkpoints/` dir (or an explicit base), rotating to `total_limit`.

        Memoized per (base_dir, keep_last_n): the manager's in-flight-step
        registry is what makes `next_step()` race-safe against a background
        committer, and that registry only protects callers sharing the SAME
        instance — a fresh manager per save_state would never see the step a
        previous call's commit still has staged."""
        if base_dir is None:
            if self.project_dir is None:
                raise ValueError("checkpoint_manager needs a project_dir or an explicit base_dir")
            base_dir = os.path.join(self.project_dir, "checkpoints")
        key = (str(base_dir), self.project_configuration.total_limit)
        cache = getattr(self, "_checkpoint_managers", None)
        if cache is None:
            cache = self._checkpoint_managers = {}
        if key not in cache:
            cache[key] = CheckpointManager(base_dir, keep_last_n=key[1])
        return cache[key]

    def _write_state_artifacts(
        self, output_dir: str, save_model_kwargs: Optional[dict] = None, sharded: bool = False
    ):
        """Write every state artifact into `output_dir` (all processes). The
        caller owns directory-level atomicity/commit. `sharded=True` routes
        through the snapshot writer so each process lands only its addressable
        shards in its own `host_*/` subdirectory."""
        for hook in self._save_model_hooks:
            hook(self._models, None, output_dir)

        rng_key = self._models[0]._rng if self._models else None
        if sharded:
            snapshot = snapshot_accelerator_state(
                self._models,
                self._optimizers,
                self._schedulers,
                self._dataloaders,
                rng_key=rng_key,
                sharded=True,
                custom_objects=tuple(self._custom_objects),
            )
            write_accelerator_snapshot(
                snapshot,
                output_dir,
                process_index=self.process_index,
                num_processes=self.num_processes,
                is_main=self.is_main_process,
                save_on_each_node=self.project_configuration.save_on_each_node,
            )
            return
        save_accelerator_state(
            output_dir,
            self._models,
            self._optimizers,
            self._schedulers,
            self._dataloaders,
            rng_key=rng_key,
            save_on_each_node=self.project_configuration.save_on_each_node,
            state_dict_type=getattr(self.state.fsdp_plugin, "state_dict_type", None)
            or "SHARDED_STATE_DICT",
        )
        for i, obj in enumerate(self._custom_objects):
            if self.is_main_process:
                save_custom_state(obj, output_dir, i)

    def maybe_save_state(self, output_dir: Optional[str] = None, **save_kwargs) -> Optional[str]:
        """Step-boundary checkpoint driver for the `save_interval` cadence:
        call once per training step; it times the step gap, asks the
        controller whether a save is due, and — when it is — runs
        `save_state()` and feeds the controller the goodput ledger's measured
        blocking cost (for `save_interval="auto"`, that measurement is what
        sets the NEXT interval against the `lost_checkpoint_s` budget).
        Returns the checkpoint path when a save ran, else None."""
        if self.save_controller is None:
            raise RuntimeError(
                "maybe_save_state() needs a cadence: construct the Accelerator with "
                'save_interval="auto" (goodput-driven) or save_interval=<steps>'
            )
        now = time.perf_counter()
        if self._last_step_boundary is not None:
            self.save_controller.observe_step(now - self._last_step_boundary)
        self._last_step_boundary = now
        self._steps_since_save += 1
        if not self.save_controller.should_save(self._steps_since_save):
            return None
        charged_before = self.timeline.goodput()["lost_s"].get("checkpoint", 0.0)
        t0 = time.perf_counter()
        path = self.save_state(output_dir, **save_kwargs)
        blocked = time.perf_counter() - t0
        charged = self.timeline.goodput()["lost_s"].get("checkpoint", 0.0) - charged_before
        # The ledger's charge IS the blocking cost (async saves charge only
        # snapshot+barrier); fall back to the local wall clock if a custom
        # timeline did not record one.
        self.save_controller.observe_save(charged if charged > 0 else blocked)
        self._steps_since_save = 0
        self._last_step_boundary = time.perf_counter()  # save time is not step time
        return path

    def save_state(
        self,
        output_dir: Optional[str] = None,
        async_save: Optional[bool] = None,
        sharded: Optional[bool] = None,
        **save_model_kwargs,
    ) -> str:
        """Save everything prepared + registered (reference accelerator.py:2830).

        With `automatic_checkpoint_naming`, commits
        `{project_dir}/checkpoints/checkpoint_{iteration}` through
        `CheckpointManager`: artifacts stage in a hidden temp dir, a per-file
        SHA-256 manifest is written, the directory is renamed into place
        atomically, the `latest` pointer advances, and rotation keeps
        `total_limit`. A kill at ANY byte offset leaves only committed
        checkpoints visible. An explicit `output_dir` writes in place (each
        artifact individually atomic) and finishes with the digest manifest so
        `load_state` can verify it.

        `async_save`/`sharded` override the Accelerator-level knobs per call.
        An async save blocks only for the device->host snapshot (plus a barrier
        on the previous commit if it is still in flight); the atomic commit
        pipeline runs on a background thread, its wall-clock lands in
        `checkpoint_async_commit_seconds` (a `checkpoint.commit` span) instead
        of the goodput ledger, and a FAILED commit surfaces as
        `CheckpointCommitError` on the next save/`drain_checkpoints()` — never
        silently dropped. The returned path is where the checkpoint WILL
        publish; call `drain_checkpoints()` before reading it."""
        async_save = self.async_save if async_save is None else bool(async_save)
        sharded = self.sharded_save if sharded is None else bool(sharded)
        if async_save:
            return self._save_state_async(output_dir, sharded, **save_model_kwargs)
        t0 = time.perf_counter()
        try:
            with self.tracer.span(
                "checkpoint.save", category="checkpoint", step=int(self.save_iteration)
            ):
                result = self._save_state_inner(output_dir, sharded=sharded, **save_model_kwargs)
        finally:
            # Goodput ledger: checkpoint saves are wall clock the run paid that
            # was not a training step (docs/observability.md) — charged even
            # when the save fails (failed-save time is still lost time).
            self.timeline.charge("checkpoint", time.perf_counter() - t0)
        # Completion instruments bump only on SUCCESS: a raised save must not
        # look like a usable checkpoint on a dashboard.
        self._m_ckpt_saves.inc()
        self._m_ckpt_seconds.observe(time.perf_counter() - t0)
        return result

    def _save_state_inner(
        self, output_dir: Optional[str] = None, sharded: bool = False, **save_model_kwargs
    ) -> str:
        if self.project_configuration.automatic_checkpoint_naming:
            manager = self.checkpoint_manager()
            logger.info(
                "Saving current state to %s (checkpoint_%d)", manager.base_dir, self.save_iteration
            )
            output_dir = manager.save(
                self.save_iteration,
                lambda staging: self._write_state_artifacts(staging, save_model_kwargs, sharded),
                is_main=self.is_main_process,
                barrier=self.wait_for_everyone,
                manifest_extra=sharded_manifest_extra(self.num_processes) if sharded else None,
            )
            self.project_configuration.iteration += 1
            return output_dir
        if output_dir is None:
            raise ValueError("output_dir is required when automatic_checkpoint_naming is off")
        self.wait_for_everyone()
        os.makedirs(output_dir, exist_ok=True)
        logger.info("Saving current state to %s", output_dir)
        self._write_state_artifacts(output_dir, save_model_kwargs, sharded)
        self.wait_for_everyone()  # every process's artifacts land before the digest scan
        if self.is_main_process:
            write_checkpoint_manifest(
                output_dir, extra=sharded_manifest_extra(self.num_processes) if sharded else None
            )
        self.project_configuration.iteration += 1
        return output_dir

    # ------------------------------------------------------------------ async checkpointing
    def _committer(self) -> AsyncCommitter:
        if self._async_committer is None:
            self._async_committer = AsyncCommitter()
        return self._async_committer

    def _save_state_async(
        self, output_dir: Optional[str], sharded: bool, **save_model_kwargs
    ) -> str:
        """Snapshot-then-commit: the train loop pays only for (a) a barrier on
        the PREVIOUS commit when it is still in flight and (b) the device->host
        state snapshot; serialize+fsync+atomic-publish run on the background
        committer. Only the blocking portion charges the goodput ledger."""
        if self.num_processes > 1 and not sharded:
            raise ValueError(
                "async_save with num_processes > 1 requires sharded=True: the background "
                "committer cannot run collective barriers, so cross-host commits "
                "coordinate through the per-host shard sentinels"
            )
        t0 = time.perf_counter()
        committer = self._committer()
        step = int(self.save_iteration)
        try:
            with self.tracer.span(
                "checkpoint.save", category="checkpoint", step=step, mode="async"
            ):
                # The barrier: the previous async commit must finish before its
                # successor snapshots (one in-flight commit bounds host memory),
                # and ITS failure surfaces here instead of being dropped.
                committer.wait()
                if self._save_model_hooks:
                    logger.warning(
                        "async_save runs registered save-state hooks on the committer "
                        "thread against live objects; use synchronous saves if a hook "
                        "reads state that training mutates"
                    )
                rng_key = self._models[0]._rng if self._models else None
                snapshot = snapshot_accelerator_state(
                    self._models,
                    self._optimizers,
                    self._schedulers,
                    self._dataloaders,
                    rng_key=rng_key,
                    sharded=sharded,
                    custom_objects=tuple(self._custom_objects),
                )
                if self.project_configuration.automatic_checkpoint_naming:
                    manager = self.checkpoint_manager()
                    final = os.path.join(manager.base_dir, f"checkpoint_{step}")

                    def writer(abort):
                        manager.save(
                            step,
                            lambda staging: self._commit_snapshot(staging, snapshot, abort),
                            is_main=self.is_main_process,
                            abort=abort,
                            manifest_extra=sharded_manifest_extra(self.num_processes)
                            if sharded
                            else None,
                        )
                else:
                    if output_dir is None:
                        raise ValueError(
                            "output_dir is required when automatic_checkpoint_naming is off"
                        )
                    final = str(output_dir)

                    def writer(abort):
                        os.makedirs(final, exist_ok=True)
                        self._commit_snapshot(final, snapshot, abort)
                        if self.is_main_process:
                            write_checkpoint_manifest(
                                final,
                                extra=sharded_manifest_extra(self.num_processes)
                                if sharded
                                else None,
                            )

                self.project_configuration.iteration += 1
        finally:
            # Only the BLOCKING portion is goodput-lost step time; the
            # background commit reports through checkpoint_async_commit_seconds.
            blocking = time.perf_counter() - t0
            self.timeline.charge("checkpoint", blocking)
        self._m_ckpt_seconds.observe(blocking)
        logger.info("Async save of step %d accepted; committing to %s in background", step, final)

        def timed_commit(abort):
            c0 = time.perf_counter()
            self._g_ckpt_in_flight.set(1)
            try:
                with self.tracer.span(
                    "checkpoint.commit", category="checkpoint", step=step, mode="async"
                ):
                    writer(abort)
            finally:
                self._g_ckpt_in_flight.set(0)
                self._m_ckpt_commit_seconds.observe(time.perf_counter() - c0)
            self._m_ckpt_saves.inc()  # success only, like the sync path

        committer.submit(timed_commit, label=f"checkpoint_{step}")
        return final

    def _commit_snapshot(self, output_dir: str, snapshot: dict, abort=None):
        """Committer-thread artifact writer: save hooks (live objects — see the
        async_save warning) + the snapshot serialization."""
        for hook in self._save_model_hooks:
            hook(self._models, None, output_dir)
        write_accelerator_snapshot(
            snapshot,
            output_dir,
            process_index=self.process_index,
            num_processes=self.num_processes,
            is_main=self.is_main_process,
            save_on_each_node=self.project_configuration.save_on_each_node,
            abort=abort,
        )

    def drain_checkpoints(self, timeout: Optional[float] = None):
        """Barrier on the in-flight async commit. Raises `CheckpointCommitError`
        if it failed — the failure-surfacing contract's shutdown edge: call
        before reading a just-saved checkpoint, at end of training, or before a
        preemption handoff."""
        if self._async_committer is not None:
            self._async_committer.drain(timeout)

    def poll_async_checkpoint(self):
        """Non-blocking: re-raise a process-death-class failure (an injected
        kill, KeyboardInterrupt) from the background committer. Ordinary commit
        failures keep to the barrier contract and surface at the next
        save/drain. Call at step boundaries (chaos and supervised loops do)."""
        if self._async_committer is not None:
            self._async_committer.poll()

    def abort_async_checkpoint(self, timeout: float = 30.0):
        """Hard shutdown: abort the in-flight commit (it will NOT publish) and
        join without raising. Returns the commit's stored failure, if any. The
        committer is single-use after an abort; the next async save builds a
        fresh one."""
        committer, self._async_committer = self._async_committer, None
        if committer is None:
            return None
        return committer.abort_and_join(timeout)

    def load_state(self, input_dir: Optional[str] = None, **load_model_kwargs):
        """(reference accelerator.py:2995)

        `input_dir` may be: a concrete checkpoint directory (digest-verified when
        it carries a manifest), a `CheckpointManager` base directory or the
        literal `"latest"` / `None` (with `automatic_checkpoint_naming`) — both
        resolve to the newest checkpoint that VERIFIES, falling back past a
        corrupted newest one to the last good save."""
        t0 = time.perf_counter()
        try:
            with self.tracer.span("checkpoint.load", category="checkpoint"):
                result = self._load_state_inner(input_dir, **load_model_kwargs)
        finally:
            # Restart-recovery time (resume after a preemption/crash respawn)
            # charges the goodput ledger's "restart" cause; the supervisor-side
            # downtime is `fault_tolerance.Supervisor.downtime_s`.
            self.timeline.charge("restart", time.perf_counter() - t0)
        self._m_ckpt_loads.inc()  # completions only, like saves
        return result

    def _load_state_inner(self, input_dir: Optional[str] = None, **load_model_kwargs):
        # A resume in the same process as an async save must see the commit
        # land (or fall back past it) — resolve() before the drain could miss
        # the newest checkpoint. A FAILED commit downgrades to a warning: the
        # whole point of resolve() is falling back to the last good save.
        try:
            self.drain_checkpoints()
        except CheckpointCommitError as exc:
            logger.warning("async commit failed before load_state (%s); resolving past it", exc)
        if input_dir == "latest":
            input_dir = None
        if input_dir is None:
            if not self.project_configuration.automatic_checkpoint_naming and self.project_dir is None:
                raise ValueError("input_dir is required when automatic_checkpoint_naming is off")
            input_dir = self.checkpoint_manager().resolve()
        else:
            input_dir = str(input_dir)
            if CheckpointManager.is_manager_dir(input_dir):
                # A manager base (e.g. a preemption checkpoint root): newest
                # verified checkpoint inside it.
                input_dir = CheckpointManager(input_dir).resolve()
            else:
                input_dir = self.checkpoint_manager(os.path.dirname(input_dir) or ".").resolve(input_dir)
        if self.project_configuration.automatic_checkpoint_naming:
            # Resume numbering after the restored checkpoint so the next save_state
            # doesn't collide with an existing directory.
            nums = re.findall(r"(\d+)(?=[^\/]*$)", str(input_dir))
            if nums:
                self.project_configuration.iteration = int(nums[0]) + 1
        logger.info("Loading states from %s", input_dir)

        for hook in self._load_model_hooks:
            hook(self._models, input_dir)

        if is_sharded_checkpoint_dir(input_dir):
            # Per-host sharded checkpoint: gather-on-load assembles each tree
            # from every host's shard files, then placement re-shards onto the
            # CURRENT mesh — the same code path restores a pod checkpoint on
            # its own topology or on a single recovery host.
            rng_key = load_sharded_accelerator_state(
                input_dir, self._models, self._optimizers, self._schedulers, self._dataloaders
            )
        else:
            rng_key = load_accelerator_state(
                input_dir, self._models, self._optimizers, self._schedulers, self._dataloaders
            )
        if rng_key is not None and self._models:
            self._models[0]._rng = rng_key
        for i, obj in enumerate(self._custom_objects):
            load_custom_state(obj, input_dir, i)

    def save_model(
        self,
        model: PreparedModel,
        save_directory: str,
        safe_serialization: bool = True,
        max_shard_size="5GB",
    ):
        """Export just the weights (reference save_model accelerator.py:2691).

        `safe_serialization=True` (default) writes (sharded) safetensors with an
        HF-style index via `save_model_safetensors` — parameters stream to host
        one tensor at a time, so a fully-sharded model never gathers whole.
        `FullyShardedDataParallelPlugin.state_dict_type` picks the multi-host
        behavior: FULL_STATE_DICT allgathers non-addressable params per-tensor
        and writes one logical state dict from the main process;
        SHARDED_STATE_DICT (default) keeps non-addressable params distributed
        and writes per-shard via orbax/tensorstore (the
        torch.distributed.checkpoint equivalent, reference utils/fsdp_utils.py:85).
        """
        from .checkpointing import _all_addressable, save_model_safetensors, save_pytree, save_sharded

        if not isinstance(safe_serialization, bool):
            # HF-reference positional order, save_model(model, dir, max_shard_size,
            # safe_serialization): a non-bool third argument is a shard size from
            # code ported off the reference — honor it instead of silently
            # truth-testing a string.
            shard_size = safe_serialization
            safe_serialization = max_shard_size if isinstance(max_shard_size, bool) else True
            max_shard_size = shard_size
        os.makedirs(save_directory, exist_ok=True)
        params = model.state_dict()
        if not safe_serialization:
            if self.is_main_process:
                save_pytree(params, os.path.join(save_directory, "model.npz"))
            return
        state_dict_type = getattr(self.state.fsdp_plugin, "state_dict_type", None) or "FULL_STATE_DICT"
        if not _all_addressable(params) and state_dict_type == "SHARDED_STATE_DICT":
            save_sharded(params, os.path.join(save_directory, "model.sharded"))
            return
        save_model_safetensors(params, save_directory, max_shard_size=max_shard_size)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        """(reference accelerator.py:3274)"""
        return skip_first_batches(dataloader, num_batches)

"""Sharded data pipeline (L3).

TPU-native redesign of reference data_loader.py (1149 LoC). The pipeline has three
stages, mirroring the reference's contracts but producing **global jax.Arrays** instead
of per-rank torch tensors:

  1. *Index plane* — `BatchSamplerShard` / `IterableDatasetShard` split the global batch
     stream across **host processes** (reference data_loader.py:100,256). All the
     even_batches / split_batches semantics live here, in pure python, exhaustively
     unit-testable without devices.
  2. *Host plane* — `DataLoaderShard` (reference :391) iterates per-host batches (from a
     torch DataLoader, our built-in loader, or any iterable), synchronizes host RNG at
     epoch start, and runs the one-batch lookahead that drives
     `GradientState.end_of_dataloader` / `remainder` (reference :445-476,377-384).
  3. *Device plane* — each host batch becomes a global array via
     `jax.make_array_from_process_local_data` with the batch axis sharded over
     ("data","fsdp"), double-buffered by a background prefetch thread — the
     MpDeviceLoader replacement (reference :518-559): jit consumes step N while step N+1
     is transferring.

`DataLoaderDispatcher` (reference :562) keeps the rank-0-reads-all mode: process 0
fetches the global batch and broadcasts; other hosts slice their shard.
"""

from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional

import numpy as np

from .logging import get_logger
from .state import AcceleratorState, GradientState, PartialState
from .utils.imports import is_torch_available
from .utils.operations import recursively_apply, send_to_device
from .utils.random import synchronize_rng_states

logger = get_logger(__name__)


class SeedableRandomSampler:
    """Deterministic shuffle keyed on `seed + epoch` (reference data_loader.py:67-97).

    Every host constructs the same permutation (numpy Philox keyed on the shared seed),
    which is what makes host-sharded loading consistent without a broadcast.
    """

    def __init__(self, data_source=None, num_samples: Optional[int] = None, seed: int = 0, epoch: int = 0):
        if num_samples is None:
            num_samples = len(data_source)
        self.num_samples = num_samples
        self.seed = seed
        self.epoch = epoch

    def __len__(self):
        return self.num_samples

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def state_dict(self) -> dict:
        return {"seed": self.seed, "epoch": self.epoch}

    def load_state_dict(self, state: dict):
        self.seed = state["seed"]
        self.epoch = state["epoch"]

    def __iter__(self):
        # The epoch is advanced externally: DataLoaderShard calls `set_epoch(iteration)`
        # at the start of each pass (reference data_loader.py:450), so standalone use
        # repeats the same order — same contract as a torch sampler.
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.num_samples).tolist()


class BatchSampler:
    """Minimal batch sampler over an index sampler (torch-free building block)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)


class BatchSamplerShard:
    """Shard a stream of index batches across host processes
    (reference data_loader.py:100-253; the shard math is the most test-enumerated
    surface in the reference suite, tests/test_data_loader.py).

    Two modes:
      - `split_batches=False` (default): the inner sampler yields *process-level*
        batches; consecutive groups of `num_processes` batches form one global step, and
        this process takes the `process_index`-th batch of each group.
      - `split_batches=True`: the inner sampler yields *global* batches of size
        `batch_size`; this process takes its contiguous `batch_size/num_processes` slice
        of every batch.

    `even_batches=True` pads the tail by cycling samples from the start of the epoch so
    every process sees the same number of equally-sized batches (jit-stable shapes); the
    duplicated count is exposed through `GradientState.remainder` for
    `gather_for_metrics` truncation.
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_processes != 0:
                raise ValueError(
                    f"To use `split_batches=True`, the batch size ({batch_sampler.batch_size}) "
                    f"must be a round multiple of the number of processes ({num_processes})."
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        if self.split_batches:
            return len(self.batch_sampler)
        length = len(self.batch_sampler)
        if length % self.num_processes == 0:
            return length // self.num_processes
        elif self.even_batches and not self.drop_last:
            return math.ceil(length / self.num_processes)
        elif self.drop_last:
            return length // self.num_processes
        else:
            # Uneven: this process may get one more batch than others.
            return length // self.num_processes + (1 if self.process_index < length % self.num_processes else 0)

    def __iter__(self):
        return self._iter_with_split() if self.split_batches else self._iter_with_no_split()

    def _iter_with_split(self):
        initial_data = []
        batch_length = None
        full_size = None
        for idx, batch in enumerate(self.batch_sampler):
            if idx == 0:
                initial_data = list(batch)
                # Slice size comes from the declared batch_size, not the observed batch —
                # a short *first* batch must not shrink every process's shard.
                full_size = self.batch_size or len(batch)
                batch_length = full_size // self.num_processes
            start = batch_length * self.process_index
            end = batch_length * (self.process_index + 1)
            if len(batch) == full_size:
                yield batch[start:end]
            elif self.drop_last:
                continue
            elif not self.even_batches:
                chunk = batch[start:end]
                if len(chunk) > 0:
                    yield chunk
            else:
                # Cycle from the epoch's first samples to refill to full size
                # (reference _iter_with_split data_loader.py:186-205).
                batch = list(batch)
                while len(batch) < full_size:
                    batch += initial_data[: full_size - len(batch)]
                yield batch[start:end]

    def _iter_with_no_split(self):
        initial_data = []
        group = []
        batch_size_seen = None
        for idx, batch in enumerate(self.batch_sampler):
            if idx < self.num_processes:
                initial_data += list(batch)
            if batch_size_seen is None:
                batch_size_seen = len(batch)
            group.append(list(batch))
            if len(group) == self.num_processes:
                # Only a full-sized final batch may pass through unchecked; a short one
                # is handled in the tail logic below.
                if len(group[-1]) == batch_size_seen or not self.even_batches:
                    yield group[self.process_index]
                    group = []
                    continue
                group_tail = group
                group = []
                yield from self._finish_tail(group_tail, initial_data, batch_size_seen)
                return
        if len(group) > 0:
            yield from self._finish_tail(group, initial_data, batch_size_seen)

    def _finish_tail(self, group, initial_data, batch_size_seen):
        if self.drop_last:
            # Drop incomplete global step entirely only if short; a complete group of
            # full batches was already yielded above.
            full = [b for b in group if len(b) == batch_size_seen]
            if len(full) == self.num_processes:
                yield full[self.process_index]
            return
        if not self.even_batches:
            if self.process_index < len(group):
                yield group[self.process_index]
            return
        # Pad: top up the short batch, then append cycled batches until the group is full.
        cycle = itertools.cycle(initial_data)
        for b in group:
            while len(b) < batch_size_seen:
                b.append(next(cycle))
        while len(group) < self.num_processes:
            group.append([next(cycle) for _ in range(batch_size_seen)])
        yield group[self.process_index]


class IterableDatasetShard:
    """Shard an iterable dataset by slicing each global batch
    (reference data_loader.py:256-352).

    Collects `batch_size * num_processes` samples (or `batch_size` when
    `split_batches=True`) and yields this process's contiguous slice. The tail is padded
    by cycling the first collected samples when `even_batches=True`.
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and batch_size % num_processes != 0:
            raise ValueError(
                f"To use `split_batches=True`, the batch size ({batch_size}) must be a round "
                f"multiple of the number of processes ({num_processes})."
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches

    def set_epoch(self, epoch: int):
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        n = len(self.dataset)
        real_batch = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        per_proc = real_batch // self.num_processes
        full_batches = n // real_batch
        tail = n % real_batch
        if self.drop_last or tail == 0:
            return full_batches * per_proc
        if self.even_batches:
            return (full_batches + 1) * per_proc
        # Uneven tail: this process gets its surviving slice of the short batch.
        start = self.process_index * per_proc
        end = start + per_proc
        return full_batches * per_proc + max(0, min(end, tail) - start)

    def __iter__(self):
        real_batch_size = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        process_slice_size = real_batch_size // self.num_processes
        start = self.process_index * process_slice_size
        end = start + process_slice_size

        first_batch = None
        current_batch = []
        for element in self.dataset:
            current_batch.append(element)
            if len(current_batch) == real_batch_size:
                yield from current_batch[start:end]
                if first_batch is None:
                    first_batch = current_batch.copy()
                current_batch = []
        if not self.drop_last and len(current_batch) > 0:
            if not self.even_batches:
                yield from current_batch[start:min(end, len(current_batch))]
                return
            if first_batch is None:
                first_batch = current_batch.copy()
            cycle = itertools.cycle(first_batch)
            while len(current_batch) < real_batch_size:
                current_batch.append(next(cycle))
            yield from current_batch[start:end]


def _default_collate(samples: List[Any]):
    """numpy-stacking collate for the built-in loader (torch-free default_collate)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([s[i] for s in samples]) for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class SimpleDataLoader:
    """Built-in map-style loader: dataset + batch_sampler → collated host batches.

    The torch-free backend for `prepare_data_loader`; torch DataLoaders are instead
    rebuilt with a sharded batch sampler (keeping their worker pool / collate_fn).

    When the dataset is columnar (`native.loader.ArrayDataset`) and the collate is
    the default, batches are assembled by the native gather pool — the sampled rows
    of every column copied into preallocated batch buffers on C++ threads, one batch
    ahead (the C++ analogue of torch's worker pool; results are bit-identical to the
    per-row Python path)."""

    def __init__(self, dataset, batch_sampler, collate_fn: Optional[Callable] = None):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or _default_collate
        self._gather_pool = None

    def __len__(self):
        return len(self.batch_sampler)

    def _columnar(self) -> bool:
        from .native.loader import ArrayDataset

        return isinstance(self.dataset, ArrayDataset) and self.collate_fn is _default_collate

    def __iter__(self):
        if self._columnar():
            yield from self._native_iter()
            return
        for batch_indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in batch_indices])

    def _native_iter(self):
        from .native.loader import NativeGatherPool, iter_gather_batches

        if self._gather_pool is None:
            self._gather_pool = NativeGatherPool()
        yield from iter_gather_batches(self._gather_pool, self.dataset.columns, self.batch_sampler)


class _IterableAsLoader:
    """Adapter: an (already-sharded) iterable dataset + batch size → collated batches."""

    def __init__(self, dataset, batch_size: int, collate_fn: Optional[Callable] = None, drop_last: bool = False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last

    def __len__(self):
        return math.ceil(len(self.dataset) / self.batch_size)

    def __iter__(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)


def _to_numpy_batch(batch):
    """Torch tensors / lists → numpy leaves (host plane is numpy everywhere)."""

    def _conv(t):
        if hasattr(t, "detach") and hasattr(t, "numpy"):
            return t.detach().cpu().numpy()
        return np.asarray(t)

    def _is_leaf(t):
        return (
            hasattr(t, "detach")
            and hasattr(t, "numpy")
            or isinstance(t, (np.ndarray, np.generic))
        )

    return recursively_apply(_conv, batch, test_type=_is_leaf)


def pad_batch_to_size(batch, target_size: int):
    """Pad every leaf's axis 0 up to `target_size` by cycling the batch's own samples.

    Keeps every step the same shape (one jit compilation, divisible device sharding);
    the duplicated tail is dropped again by `gather_for_metrics` via
    `GradientState.remainder` (reference pads at the sampler plane instead —
    data_loader.py:186-253 — because its batch is per-rank; ours is per-host and must
    also divide the local device count)."""

    def _pad(t):
        if t.ndim == 0 or t.shape[0] >= target_size:
            return t
        reps = int(np.ceil(target_size / t.shape[0]))
        return np.concatenate([t] * reps, axis=0)[:target_size]

    def _is_leaf(t):
        return isinstance(t, (np.ndarray, np.generic))

    return recursively_apply(_pad, batch, test_type=_is_leaf)


def batch_to_global_array(batch, sharding):
    """Host batch → global jax.Array with the given input sharding.

    The `MpDeviceLoader`/`send_to_device` replacement (reference data_loader.py:518-559):
    under SPMD each host contributes its local shard and the result is one logical array
    spanning the mesh. Non-array leaves pass through untouched.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def _make(t):
        t = np.asarray(t)
        if t.ndim == 0:
            return jax.device_put(t)
        try:
            return jax.make_array_from_process_local_data(sharding, t)
        except ValueError:
            # Batch smaller than (or not divisible by) the data-axis device count —
            # legal for tiny single-host eval batches; replicate instead of sharding
            # dim 0. Multi-host must not take this path: each host holds *different*
            # local data, and a replicated global array would silently diverge.
            if jax.process_count() > 1:
                raise ValueError(
                    f"Per-host batch dim {t.shape[0]} does not match the data-axis sharding "
                    f"{sharding.spec} on a multi-host mesh. Use even_batches=True (pads to a "
                    "stable per-host batch) or make the batch divisible by the local "
                    "data-parallel device count."
                )
            logger.warning_once(
                "Batch dim %d is not divisible by the data-axis device count; replicating the batch. "
                "For full throughput make the per-host batch a multiple of the local data-parallel size.",
                t.shape[0],
            )
            replicated = NamedSharding(sharding.mesh, PartitionSpec())
            return jax.make_array_from_process_local_data(replicated, t)

    def _is_leaf(t):
        return isinstance(t, (np.ndarray, np.generic))

    return recursively_apply(_make, batch, test_type=_is_leaf)


class DataLoaderStateMixin:
    """begin/end hooks registering with GradientState (reference data_loader.py:355-388)."""

    def __init_subclass__(cls, **kwargs):
        cls.end_of_dataloader = False
        cls.remainder = -1

    def reset(self):
        self.end_of_dataloader = False
        self.remainder = -1

    def begin(self):
        self.reset()
        length = self.total_dataset_length
        if length is not None and self.total_batch_size:
            self.remainder = length % self.total_batch_size
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)


class DataLoaderShard(DataLoaderStateMixin):
    """Per-host loader producing global device arrays (reference data_loader.py:391-515).

    Wraps a host-batch producer (rebuilt torch DataLoader / SimpleDataLoader / iterable):
      - epoch-start host RNG sync (reference :447)
      - one-batch lookahead setting `end_of_dataloader` on the final batch (:469-473)
      - device plane: global-array formation + background prefetch
    """

    def __init__(
        self,
        base_loader,
        sharding=None,
        device_placement: bool = True,
        rng_types: Optional[List[str]] = None,
        synchronized_generator=None,
        total_batch_size: Optional[int] = None,
        total_dataset_length: Optional[int] = None,
        prefetch_size: int = 2,
        skip_batches: int = 0,
        per_host_batch_size: Optional[int] = None,
        even_batches: bool = True,
        _non_blocking: bool = True,
    ):
        self.base_loader = base_loader
        self.sharding = sharding
        self.device_placement = device_placement
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.gradient_state = GradientState()
        self._total_batch_size = total_batch_size
        self._total_dataset_length = total_dataset_length
        # prefetch_size=0 means SYNCHRONOUS: no producer thread, batches are
        # collated + transferred inline on the consumer — the debugging mode
        # (clean stack traces, no thread interleaving). >=1 sizes the background
        # prefetch queue. (0 used to be silently clamped to 1.)
        if prefetch_size < 0:
            raise ValueError(f"prefetch_size must be >= 0 (0 = synchronous), got {prefetch_size}")
        self.prefetch_size = prefetch_size
        self.skip_batches = skip_batches
        self.per_host_batch_size = per_host_batch_size
        self.even_batches = even_batches
        self.iteration = 0

    # -- reference-parity introspection (data_loader.py:497-515) -----------------------
    @property
    def total_batch_size(self):
        return self._total_batch_size

    @property
    def total_dataset_length(self):
        if self._total_dataset_length is not None:
            return self._total_dataset_length
        dataset = getattr(self.base_loader, "dataset", None)
        try:
            return len(dataset) if dataset is not None else None
        except TypeError:
            return None

    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", None)

    @property
    def batch_sampler(self):
        return getattr(self.base_loader, "batch_sampler", None)

    def _advance_linked_loader(self):
        """A `skip_first_batches` wrapper finishing its (partial) pass advances
        the loader it was built from, so the caller's NEXT full pass over the
        original loader draws a fresh permutation instead of replaying the
        resumed epoch's order."""
        linked = getattr(self, "_linked_loader", None)
        if linked is not None:
            linked.iteration = max(linked.iteration, self.iteration)

    def set_epoch(self, epoch: int):
        """Pin the shuffle epoch for the NEXT pass (public resume API: also
        realigns the loader's own pass counter, which `__iter__` would
        otherwise feed to the sampler — so an explicit `set_epoch(E)` wins
        over however many passes this loader object has or hasn't run)."""
        self.iteration = epoch
        if hasattr(self.batch_sampler, "sampler") and hasattr(self.batch_sampler.sampler, "set_epoch"):
            self.batch_sampler.sampler.set_epoch(epoch)
        elif hasattr(self.batch_sampler, "batch_sampler") and hasattr(
            getattr(self.batch_sampler.batch_sampler, "sampler", None), "set_epoch"
        ):
            self.batch_sampler.batch_sampler.sampler.set_epoch(epoch)
        elif hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        return max(0, len(self.base_loader) - self.skip_batches)

    def _process_batch(self, batch):
        batch = _to_numpy_batch(batch)
        if self.even_batches and self.per_host_batch_size is not None:
            batch = pad_batch_to_size(batch, self.per_host_batch_size)
        if self.device_placement:
            if self.sharding is not None:
                return batch_to_global_array(batch, self.sharding)
            return send_to_device(batch)
        return batch

    def _raw_iter(self):
        for idx, batch in enumerate(self.base_loader):
            if idx < self.skip_batches:
                continue
            yield batch

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.synchronized_generator)
        self.set_epoch(self.iteration)
        self.begin()
        if self.prefetch_size == 0:
            # Synchronous debug mode: no producer thread. Same one-batch
            # lookahead so `end_of_dataloader` is still set before the final
            # batch is yielded (the gradient-sync contract).
            try:
                held = None
                for raw in self._raw_iter():
                    batch = self._process_batch(raw)
                    if held is not None:
                        yield held
                    held = batch
                if held is not None:
                    self.end_of_dataloader = True
                    yield held
                self.iteration += 1
                self._advance_linked_loader()
            finally:
                self.end()
            return
        # Background prefetch: a producer thread collates + transfers up to
        # `prefetch_size` batches ahead so host work and host→HBM DMA overlap with the
        # consumer's jitted compute (the MpDeviceLoader replacement, reference
        # data_loader.py:518-559). One batch is held back so `end_of_dataloader` is set
        # *before* the final batch is yielded (lookahead contract, reference :469-473).
        stop = threading.Event()
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_size)

        def _producer():
            try:
                for raw in self._raw_iter():
                    item = ("item", self._process_batch(raw))
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                q.put(("end", None))
            except BaseException as e:  # surfaced on the consumer thread
                q.put(("error", e))

        producer = threading.Thread(target=_producer, daemon=True)
        producer.start()
        try:
            held = None
            while True:
                kind, payload = q.get()
                if kind == "error":
                    raise payload
                if kind == "end":
                    if held is not None:
                        self.end_of_dataloader = True
                        yield held
                    break
                if held is not None:
                    yield held
                held = payload
            self.iteration += 1
            self._advance_linked_loader()
        finally:
            stop.set()
            # Drain so a producer blocked on q.put can observe `stop`, then wait for it
            # to leave any in-flight device transfer — a daemon thread inside XLA at
            # interpreter shutdown aborts the process.
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            producer.join(timeout=5.0)
            self.end()


class DataLoaderDispatcher(DataLoaderStateMixin):
    """Rank-0-reads-all loader (reference data_loader.py:562-795).

    Process 0 iterates the underlying loader over the *global* batch; the batch skeleton
    travels the object plane and arrays the data plane; every host slices its shard and
    forms the same global arrays. The default for IterableDatasets (reference :883-887).
    """

    def __init__(
        self,
        base_loader,
        sharding=None,
        device_placement: bool = True,
        split_batches: bool = False,
        total_batch_size: Optional[int] = None,
        total_dataset_length: Optional[int] = None,
        skip_batches: int = 0,
        slice_fn: Optional[Callable] = None,
        per_host_batch_size: Optional[int] = None,
        even_batches: bool = True,
    ):
        self.base_loader = base_loader
        self.sharding = sharding
        self.device_placement = device_placement
        self.split_batches = split_batches
        self.state = PartialState()
        self.gradient_state = GradientState()
        self._total_batch_size = total_batch_size
        self._total_dataset_length = total_dataset_length
        self.skip_batches = skip_batches
        self.slice_fn = slice_fn
        self.per_host_batch_size = per_host_batch_size
        self.even_batches = even_batches
        self.iteration = 0

    @property
    def total_batch_size(self):
        return self._total_batch_size

    @property
    def total_dataset_length(self):
        if self._total_dataset_length is not None:
            return self._total_dataset_length
        dataset = getattr(self.base_loader, "dataset", None)
        try:
            return len(dataset) if dataset is not None else None
        except TypeError:
            return None

    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", None)

    _advance_linked_loader = DataLoaderShard._advance_linked_loader

    def set_epoch(self, epoch: int):
        """Pin the shuffle epoch for the NEXT pass (see DataLoaderShard.set_epoch)."""
        self.iteration = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        whole_length = len(self.base_loader)
        if self.split_batches or self.state.num_processes == 1:
            return max(0, whole_length - self.skip_batches)
        return max(0, math.ceil(whole_length / self.state.num_processes) - self.skip_batches)

    def _read_global_batch(self, iterator):
        """Read one *global* batch from the base loader: with `split_batches` the loader
        already yields global batches; otherwise concatenate `num_processes` consecutive
        per-process batches (reference _fetch_batches data_loader.py:618-630)."""
        from .utils.operations import concatenate

        n = 1 if (self.split_batches or self.state.num_processes == 1) else self.state.num_processes
        parts = []
        for _ in range(n):
            try:
                parts.append(_to_numpy_batch(next(iterator)))
            except StopIteration:
                break
        if not parts:
            raise StopIteration
        return parts[0] if len(parts) == 1 else concatenate(parts, dim=0)

    def _fetch_batch(self, iterator):
        """Main process reads; everyone learns (has_more, batch) via the object/data
        planes (reference _fetch_batches data_loader.py:618-660)."""
        from .utils.operations import broadcast, broadcast_object_list

        if self.state.num_processes == 1:
            try:
                return True, self._read_global_batch(iterator)
            except StopIteration:
                return False, None

        info = [None, None]  # (has_more, structure)
        batch = None
        if self.state.is_main_process:
            try:
                batch = self._read_global_batch(iterator)
                from .utils.operations import get_data_structure

                info = [True, get_data_structure(batch)]
            except StopIteration:
                info = [False, None]
        info = broadcast_object_list(info, from_process=0)
        if not info[0]:
            return False, None
        if not self.state.is_main_process:
            # Materialize zero-filled buffers matching the structure, then receive.
            def _zeros(spec):
                if isinstance(spec, dict) and set(spec) == {"shape", "dtype"}:
                    return np.zeros(spec["shape"], dtype=np.dtype(spec["dtype"]))
                if isinstance(spec, dict):
                    return {k: _zeros(v) for k, v in spec.items()}
                if isinstance(spec, (list, tuple)):
                    return type(spec)(_zeros(s) for s in spec)
                return spec

            batch = _zeros(info[1])
        batch = broadcast(batch, from_process=0)
        return True, batch

    def _slice_for_process(self, batch):
        """Pad the global batch to its stable full size FIRST, then slice — a short
        final batch sliced by observed size would drop tail samples and desync the
        remainder bookkeeping (the reference pads in _fetch_batches, data_loader.py:645)."""
        from .utils.operations import find_batch_size, slice_tensors

        batch_size = find_batch_size(batch)
        if batch_size is None:
            return batch
        full = self._total_batch_size or batch_size
        if batch_size < full:
            batch = pad_batch_to_size(batch, full)
            batch_size = full
        per_proc = batch_size // self.state.num_processes
        start = self.state.process_index * per_proc
        if self.slice_fn is not None:
            return self.slice_fn(batch, slice(start, start + per_proc), self.state.process_index, self.state.num_processes)
        return slice_tensors(batch, slice(start, start + per_proc))

    def __iter__(self):
        self.set_epoch(self.iteration)
        self.begin()
        try:
            iterator = iter(self.base_loader)
            batch_index = 0
            has_more, current = self._fetch_batch(iterator)
            while has_more:
                has_more, nxt = self._fetch_batch(iterator)
                if batch_index >= self.skip_batches:
                    if not has_more:
                        self.end_of_dataloader = True
                        from .utils.operations import find_batch_size

                        observed = find_batch_size(current)
                        if observed is not None and self._total_batch_size:
                            self.remainder = observed % self._total_batch_size or -1
                    local = self._slice_for_process(current) if self.state.num_processes > 1 else current
                    if self.even_batches and self.per_host_batch_size is not None:
                        local = pad_batch_to_size(local, self.per_host_batch_size)
                    if self.device_placement:
                        if self.sharding is not None:
                            yield batch_to_global_array(local, self.sharding)
                        else:
                            yield send_to_device(local)
                    else:
                        yield local
                current = nxt
                batch_index += 1
            self.iteration += 1
            self._advance_linked_loader()
        finally:
            self.end()


class SkipBatchSampler:
    """Batch sampler skipping the first N batches (reference data_loader.py:1037)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def __iter__(self):
        for index, samples in enumerate(self.batch_sampler):
            if index >= self.skip_batches:
                yield samples

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches


def skip_first_batches(dataloader, num_batches: int = 0):
    """Mid-epoch resume: a loader that skips its first `num_batches`
    (reference data_loader.py:1082-1149).

    When the base loader exposes a batch sampler, skipping happens at the *index plane*
    (`SkipBatchSampler`) so skipped batches are never loaded or collated; otherwise the
    wrapper skips already-collated batches."""
    if isinstance(dataloader, DataLoaderShard):
        base = dataloader.base_loader
        batch_sampler = getattr(base, "batch_sampler", None)
        new_base = None
        if batch_sampler is not None:
            skip_sampler = SkipBatchSampler(batch_sampler, num_batches)
            if _is_torch_loader(base):
                new_base = _rebuild_torch_loader(base, skip_sampler)
            elif isinstance(base, SimpleDataLoader):
                new_base = SimpleDataLoader(base.dataset, skip_sampler, base.collate_fn)
        if new_base is not None:
            skipped = DataLoaderShard(
                new_base,
                sharding=dataloader.sharding,
                device_placement=dataloader.device_placement,
                rng_types=dataloader.rng_types,
                synchronized_generator=dataloader.synchronized_generator,
                total_batch_size=dataloader._total_batch_size,
                total_dataset_length=dataloader._total_dataset_length,
                prefetch_size=dataloader.prefetch_size,
                per_host_batch_size=dataloader.per_host_batch_size,
                even_batches=dataloader.even_batches,
            )
        else:
            skipped = DataLoaderShard(
                dataloader.base_loader,
                sharding=dataloader.sharding,
                device_placement=dataloader.device_placement,
                rng_types=dataloader.rng_types,
                synchronized_generator=dataloader.synchronized_generator,
                total_batch_size=dataloader._total_batch_size,
                total_dataset_length=dataloader._total_dataset_length,
                prefetch_size=dataloader.prefetch_size,
                skip_batches=dataloader.skip_batches + num_batches,
                per_host_batch_size=dataloader.per_host_batch_size,
                even_batches=dataloader.even_batches,
            )
    elif isinstance(dataloader, DataLoaderDispatcher):
        skipped = DataLoaderDispatcher(
            dataloader.base_loader,
            sharding=dataloader.sharding,
            device_placement=dataloader.device_placement,
            split_batches=dataloader.split_batches,
            total_batch_size=dataloader._total_batch_size,
            total_dataset_length=dataloader._total_dataset_length,
            skip_batches=dataloader.skip_batches + num_batches,
            slice_fn=dataloader.slice_fn,
            per_host_batch_size=dataloader.per_host_batch_size,
            even_batches=dataloader.even_batches,
        )
    else:
        skipped = None
    if skipped is not None:
        # The resumed partial pass must shuffle with the interrupted epoch's
        # permutation, not a fresh wrapper's pass 0 — carry the source
        # loader's pass counter across (it was itself realigned by
        # load_state when resuming in a fresh process), and link back so the
        # wrapper's completed pass advances the source: the caller's next
        # full pass over the ORIGINAL loader must draw the following epoch's
        # permutation, not replay the resumed one.
        skipped.iteration = dataloader.iteration
        skipped._linked_loader = dataloader
        return skipped

    # Raw iterable / torch loader: generic skipping wrapper.
    class _Skipper:
        def __init__(self, dl, n):
            self.dl = dl
            self.n = n
            self.dataset = getattr(dl, "dataset", None)

        def __iter__(self):
            for i, b in enumerate(self.dl):
                if i >= self.n:
                    yield b

        def __len__(self):
            return max(0, len(self.dl) - self.n)

    return _Skipper(dataloader, num_batches)


def _is_torch_loader(dataloader) -> bool:
    if not is_torch_available():
        return False
    import torch.utils.data

    return isinstance(dataloader, torch.utils.data.DataLoader)


def _rebuild_torch_loader(dataloader, new_batch_sampler):
    """Rebuild a torch DataLoader around a sharded batch sampler, keeping its worker
    pool and collate_fn (the reference does the same surgery, data_loader.py:905-1010)."""
    import torch.utils.data

    kwargs = {
        "num_workers": dataloader.num_workers,
        "collate_fn": dataloader.collate_fn,
        "pin_memory": False,  # jax owns the host→device path
        "timeout": dataloader.timeout,
        "worker_init_fn": dataloader.worker_init_fn,
        "prefetch_factor": dataloader.prefetch_factor if dataloader.num_workers > 0 else None,
        "persistent_workers": dataloader.persistent_workers,
    }
    kwargs = {k: v for k, v in kwargs.items() if v is not None or k == "collate_fn"}
    return torch.utils.data.DataLoader(dataloader.dataset, batch_sampler=new_batch_sampler, **kwargs)


def default_data_sharding(mesh=None):
    """NamedSharding putting axis 0 on ("data","fsdp") — the canonical input sharding."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        mesh = AcceleratorState().mesh
    return NamedSharding(mesh, PartitionSpec(("data", "fsdp")))


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[List[str]] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch: Optional[Callable] = None,
    use_seedable_sampler: bool = True,
    data_seed: int = 42,
    sharding=None,
    prefetch_size: int = 2,
) -> DataLoaderShard | DataLoaderDispatcher:
    """Factory combining sharded sampling + host loading + device plane (reference
    data_loader.py:797-1034).

    Accepts a torch DataLoader (rebuilt with a sharded batch sampler), a
    `SimpleDataLoader`, a map-style dataset paired with an existing batch_sampler, or
    any iterable of batches (treated as an already-per-host stream).

    `prefetch_size` sizes the background producer queue (host collation +
    host→HBM DMA overlap with jitted compute); **0 disables the producer thread
    entirely** — synchronous inline batches for debugging (clean stack traces,
    no thread interleaving), at the cost of the transfer/compute overlap.
    """
    state = PartialState()
    if num_processes is None:
        num_processes = state.num_processes
    if process_index is None:
        process_index = state.process_index

    if sharding is None and put_on_device:
        sharding = default_data_sharding()

    synchronized_generator = None

    # --- torch DataLoader path --------------------------------------------------------
    if _is_torch_loader(dataloader):
        import torch.utils.data

        dataset = dataloader.dataset
        is_iterable = isinstance(dataset, torch.utils.data.IterableDataset)
        if dispatch_batches is None:
            dispatch_batches = is_iterable and num_processes > 1
        batch_size = dataloader.batch_size if dataloader.batch_size is not None else getattr(
            dataloader.batch_sampler, "batch_size", 1
        )
        total_batch_size = batch_size * (1 if split_batches else num_processes)

        per_host_bs = batch_size // num_processes if split_batches else batch_size
        if dispatch_batches:
            return DataLoaderDispatcher(
                dataloader,
                sharding=sharding,
                device_placement=put_on_device,
                split_batches=split_batches,
                total_batch_size=total_batch_size,
                slice_fn=slice_fn_for_dispatch,
                per_host_batch_size=per_host_bs,
                even_batches=even_batches,
            )
        if is_iterable:
            shard = IterableDatasetShard(
                dataset,
                batch_size=batch_size,
                drop_last=dataloader.drop_last,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
                even_batches=even_batches,
            )
            base = _IterableAsLoader(shard, per_host_bs, collate_fn=dataloader.collate_fn)
            return DataLoaderShard(
                base,
                sharding=sharding,
                device_placement=put_on_device,
                rng_types=rng_types,
                total_batch_size=total_batch_size,
                prefetch_size=prefetch_size,
                per_host_batch_size=per_host_bs,
                even_batches=even_batches,
            )
        # Map-style: swap the sampler if seedable shuffling requested, then shard batches.
        batch_sampler = dataloader.batch_sampler
        if use_seedable_sampler and isinstance(getattr(batch_sampler, "sampler", None), torch.utils.data.RandomSampler):
            seedable = SeedableRandomSampler(num_samples=len(dataset), seed=data_seed)
            synchronized_generator = seedable
            batch_sampler = BatchSampler(seedable, batch_size=batch_size, drop_last=dataloader.drop_last)
        new_batch_sampler = (
            batch_sampler
            if num_processes == 1
            else BatchSamplerShard(
                batch_sampler,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
                even_batches=even_batches,
            )
        )
        base = _rebuild_torch_loader(dataloader, new_batch_sampler)
        return DataLoaderShard(
            base,
            sharding=sharding,
            device_placement=put_on_device,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            total_batch_size=total_batch_size,
            total_dataset_length=len(dataset),
            prefetch_size=prefetch_size,
            per_host_batch_size=per_host_bs,
            even_batches=even_batches,
        )

    # --- built-in loaders (SimpleDataLoader / native columnar) ------------------------
    # One contract for both: shard the batch sampler across processes and wrap
    # in the device plane, so either loader prepared through the Accelerator
    # gets sampler checkpointing (save_state's _find_seedable_sampler walks
    # batch_sampler.sampler), epoch-synced reshuffles, dispatch_batches, and
    # the end_of_dataloader boundary. Only the base rebuild differs.
    from .native.loader import NativeArrayLoader

    if isinstance(dataloader, (SimpleDataLoader, NativeArrayLoader)):
        batch_sampler = dataloader.batch_sampler
        batch_size = getattr(batch_sampler, "batch_size", 1)
        total_batch_size = batch_size * (1 if split_batches else num_processes)
        per_host_bs = batch_size // num_processes if split_batches else batch_size
        if dispatch_batches:
            return DataLoaderDispatcher(
                dataloader,
                sharding=sharding,
                device_placement=put_on_device,
                split_batches=split_batches,
                total_batch_size=total_batch_size,
                slice_fn=slice_fn_for_dispatch,
                per_host_batch_size=per_host_bs,
                even_batches=even_batches,
            )
        if use_seedable_sampler and isinstance(getattr(batch_sampler, "sampler", None), SeedableRandomSampler):
            synchronized_generator = batch_sampler.sampler
        new_batch_sampler = (
            batch_sampler
            if num_processes == 1
            else BatchSamplerShard(
                batch_sampler,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
                even_batches=even_batches,
            )
        )
        if new_batch_sampler is batch_sampler:
            base = dataloader  # sampler unchanged: keep the loader (and any native gather pool)
        elif isinstance(dataloader, NativeArrayLoader):
            base = NativeArrayLoader(
                dataloader.dataset, new_batch_sampler, num_threads=dataloader.num_threads
            )
        else:
            base = SimpleDataLoader(
                dataloader.dataset, new_batch_sampler, collate_fn=dataloader.collate_fn
            )
        try:
            total_len = len(dataloader.dataset)
        except TypeError:
            total_len = None
        return DataLoaderShard(
            base,
            sharding=sharding,
            device_placement=put_on_device,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            total_batch_size=total_batch_size,
            total_dataset_length=total_len,
            prefetch_size=prefetch_size,
            per_host_batch_size=per_host_bs,
            even_batches=even_batches,
        )

    # Any iterable of batches: assume it already yields this host's batches.
    return DataLoaderShard(
        dataloader,
        sharding=sharding,
        device_placement=put_on_device,
        rng_types=rng_types,
        prefetch_size=prefetch_size,
    )

"""Process launchers: `notebook_launcher` and `debug_launcher`
(reference launchers.py:38-258).

TPU-native redesign. The reference must fork 8 processes in a notebook because
torch_xla drives one core per process (launchers.py:112-153, xmp.spawn); JAX is
single-controller — one process drives every local chip through SPMD — so
`notebook_launcher` validates the environment and calls the function in-process.

`debug_launcher` keeps its reference role (launchers.py:225-258: N CPU processes with a
gloo FileStore rendezvous) re-based on the JAX coordination service: it spawns N host
processes, each pinned to the CPU platform with one virtual device, rendezvousing on a
localhost coordinator with gloo cross-process CPU collectives. This is the multi-process
test harness — the only way to exercise MULTI_HOST code paths without a pod.
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile
import traceback
from typing import Callable

from .logging import get_logger

logger = get_logger(__name__)


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _debug_worker(index: int, function, args, env: dict, error_dir: str):
    """Child entry: install the env-var protocol BEFORE jax exists, then run."""
    os.environ.update(env)
    os.environ["ACCELERATE_TPU_PROCESS_ID"] = str(index)
    os.environ["ACCELERATE_TPU_LOCAL_PROCESS_INDEX"] = str(index)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        function(*args)
    except Exception:
        with open(os.path.join(error_dir, f"rank{index}.err"), "w") as f:
            f.write(traceback.format_exc())
        sys.exit(1)


def debug_launcher(function: Callable, args=(), num_processes: int = 2):
    """Launch `function(*args)` in `num_processes` host processes on CPU, rendezvoused
    through a localhost JAX coordinator (reference debug_launcher launchers.py:225-258).

    Each child is a real `jax.process_index()` rank with one CPU device and working
    cross-process collectives (gloo), so `PartialState` reports MULTI_HOST — the same
    topology shape as a TPU pod slice.
    """
    import multiprocessing

    port = _free_port()
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "ACCELERATE_TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "ACCELERATE_TPU_NUM_PROCESSES": str(num_processes),
        "ACCELERATE_TPU_DEBUG_LAUNCHER": "1",
    }
    ctx = multiprocessing.get_context("spawn")
    with tempfile.TemporaryDirectory() as error_dir:
        procs = [
            ctx.Process(target=_debug_worker, args=(i, function, args, env, error_dir))
            for i in range(num_processes)
        ]
        for p in procs:
            p.start()
        for p in procs:
            p.join()
        failed = [i for i, p in enumerate(procs) if p.exitcode != 0]
        if failed:
            msgs = []
            for i in failed:
                err_file = os.path.join(error_dir, f"rank{i}.err")
                if os.path.exists(err_file):
                    with open(err_file) as f:
                        msgs.append(f"-- process {i} --\n{f.read()}")
                else:
                    msgs.append(f"-- process {i} -- exited with code {procs[i].exitcode}")
            raise RuntimeError(
                f"debug_launcher: {len(failed)}/{num_processes} processes failed:\n" + "\n".join(msgs)
            )


def notebook_launcher(
    function: Callable,
    args=(),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
):
    """Run a training function from a notebook (reference notebook_launcher
    launchers.py:38-223).

    On TPU/GPU hosts JAX is single-controller, so the fork dance the reference does for
    torch_xla (8 procs, start_method="fork") is unnecessary: all local chips are already
    visible to this process and `function` runs here, in-process, under SPMD. Passing
    `num_processes > 1` on a CPU-only host falls back to `debug_launcher` to simulate a
    multi-host topology.
    """
    from .state import AcceleratorState, PartialState

    if AcceleratorState._shared_state or PartialState._shared_state:
        # Same guard as the reference (launchers.py:91-101): an Accelerator built
        # before launching would have claimed devices/state in this process.
        raise ValueError(
            "An `Accelerator` (or `PartialState`) already exists in this process. "
            "Restart the notebook kernel and call notebook_launcher before creating one."
        )
    if mixed_precision not in ("no", "fp16", "bf16", "fp8"):
        raise ValueError(f"Unknown mixed_precision mode: {mixed_precision!r}")
    os.environ["ACCELERATE_TPU_MIXED_PRECISION"] = mixed_precision

    import jax

    platform = jax.default_backend()
    if platform == "cpu" and num_processes is not None and num_processes > 1:
        logger.info("CPU platform: simulating %d processes via debug_launcher", num_processes)
        return debug_launcher(function, args=args, num_processes=num_processes)
    logger.info(
        "Launching in-process on %d local %s device(s) (single-controller SPMD)",
        jax.local_device_count(),
        platform,
    )
    return function(*args)

"""In-package test utilities (parity: reference test_utils/ — testing.py, training.py,
scripts/). Shipped inside the package so launched test scripts are importable
post-install, exactly as the reference does (SURVEY §4.3)."""

from .testing import (
    AccelerateTestCase,
    TempDirTestCase,
    device_count,
    execute_subprocess,
    require_multi_device,
    require_multi_process,
    require_single_device,
    require_tpu,
    skip,
)
from .training import RegressionDataset, RegressionModel, regression_loss

"""Example-drift harness (parity: reference test_utils/examples.py:63
`compare_against_test` + tests/test_examples.py::ExampleDifferenceTests).

The reference keeps every `by_feature/*` script a copy of the canonical example plus
ONE feature, and diffs them line-by-line so examples can't rot apart from the docs.
Here the same contract is enforced structurally: each by_feature script must (a)
reuse the canonical data pipeline by importing from `nlp_example` rather than
re-implementing it, (b) keep the canonical training shape (a `training_function`,
an argparse entry, the prepare() call), and (c) introduce its feature — asserted by
requiring the feature's API marker to appear.
"""

from __future__ import annotations

import ast
from pathlib import Path


def parse_example(path: str | Path):
    src = Path(path).read_text()
    return src, ast.parse(src)


def imports_canonical_dataset(tree: ast.Module) -> bool:
    """True if the script imports get_dataset (or the corpus helper) instead of
    redefining the data pipeline."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "nlp_example":
            if any(alias.name == "get_dataset" for alias in node.names):
                return True
    # Only genuinely different-domain scripts (pretraining corpora) may be
    # self-contained, and they must use the distinct `get_corpus` name — a local
    # `get_dataset` is exactly the copy-instead-of-import rot this harness catches.
    return any(
        isinstance(node, ast.FunctionDef) and node.name == "get_corpus" for node in ast.walk(tree)
    )


def toplevel_function_names(tree: ast.Module) -> set:
    return {n.name for n in tree.body if isinstance(n, ast.FunctionDef)}


def has_argparse_main(tree: ast.Module) -> bool:
    """The canonical entry shape: argparse wiring under `if __name__ == "__main__"`."""
    for node in tree.body:
        if isinstance(node, ast.If):
            test = ast.unparse(node.test).replace("'", '"')
            if test == '__name__ == "__main__"':
                return "ArgumentParser" in ast.unparse(node)
    return False


def check_example_shape(path: str | Path, feature_markers: list) -> list:
    """Return a list of drift problems (empty = conforming)."""
    src, tree = parse_example(path)
    problems = []
    if not imports_canonical_dataset(tree):
        problems.append("does not reuse the canonical dataset (import get_dataset from nlp_example)")
    if "training_function" not in toplevel_function_names(tree) and "main" not in toplevel_function_names(tree):
        problems.append("missing the canonical training_function/main entry")
    if not has_argparse_main(tree):
        problems.append("missing the canonical argparse __main__ block")
    if ".prepare(" not in src:
        problems.append("never calls accelerator.prepare()")
    missing = [m for m in feature_markers if m not in src]
    if missing:
        problems.append(f"feature marker(s) absent: {missing}")
    return problems

"""Testing harness (parity: reference test_utils/testing.py).

The two pillars: (1) singleton hygiene — `AccelerateTestCase` resets the Borg state
between tests (reference testing.py:427-438); (2) capability-gated skips —
`require_multi_device` etc. let one suite run on 1-chip CI, the 8-device virtual CPU
mesh, or a pod (reference testing.py:239-301).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import unittest
from pathlib import Path


def device_count() -> int:
    import jax

    return jax.device_count()


def skip(reason: str):
    return unittest.skip(reason)


def require_single_device(test_case):
    import jax

    return unittest.skipUnless(jax.device_count() == 1, "test requires exactly one device")(test_case)


def require_multi_device(test_case):
    import jax

    return unittest.skipUnless(jax.device_count() > 1, "test requires multiple devices")(test_case)


def require_tpu(test_case):
    import jax

    return unittest.skipUnless(jax.default_backend() == "tpu", "test requires a TPU")(test_case)


def require_multi_process(test_case):
    import jax

    return unittest.skipUnless(jax.process_count() > 1, "test requires multiple host processes")(
        test_case
    )


class AccelerateTestCase(unittest.TestCase):
    """Resets the state singletons in tearDown so tests can't leak topology/precision
    config into each other (reference testing.py:427-438)."""

    def tearDown(self):
        super().tearDown()
        from ..state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()


class TempDirTestCase(AccelerateTestCase):
    """Provides `self.tmpdir`, cleared per test (reference testing.py:394-424)."""

    clear_on_setup = True

    @classmethod
    def setUpClass(cls):
        super().setUpClass()
        cls._tmpdir_obj = tempfile.TemporaryDirectory()
        cls.tmpdir = Path(cls._tmpdir_obj.name)

    @classmethod
    def tearDownClass(cls):
        super().tearDownClass()
        cls._tmpdir_obj.cleanup()

    def setUp(self):
        super().setUp()
        if self.clear_on_setup:
            for path in sorted(self.tmpdir.glob("**/*"), reverse=True):
                if path.is_file():
                    path.unlink()
                elif path.is_dir() and not any(path.iterdir()):
                    path.rmdir()


def execute_subprocess(cmd, env=None, timeout: int = 600) -> subprocess.CompletedProcess:
    """Run a launched test script, raising with captured output on failure (reference
    execute_subprocess_async testing.py:501-560)."""
    result = subprocess.run(
        cmd,
        env=env if env is not None else os.environ.copy(),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if result.returncode == -signal.SIGABRT:
        # SIGABRT specifically is (on hosts with an injected TPU plugin) the
        # plugin's tunnel thread aborting under chip contention, not the script
        # under test. Retry once, preserving the first run's output for diagnosis.
        # Other signals (SIGINT, SIGKILL/OOM) are NOT retried.
        sys.stderr.write(
            f"[testing] {cmd[0]} died with SIGABRT; retrying once. First stderr tail:\n"
            f"{(result.stderr or '')[-2000:]}\n"
        )
        result = subprocess.run(
            cmd,
            env=env if env is not None else os.environ.copy(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    if result.returncode != 0:
        raise RuntimeError(
            f"Command {cmd} failed (exit {result.returncode})\n"
            f"--- stdout ---\n{result.stdout}\n--- stderr ---\n{result.stderr}"
        )
    return result


_COLLECTIVE_TIMEOUT_FLAG = "--xla_cpu_collective_call_terminate_timeout_seconds=600"
_collective_flag_supported = None  # process-level memo over the on-disk probe cache


def _supports_collective_timeout_flag() -> bool:
    """Whether this jaxlib's XLA accepts the collective-timeout flag. Unknown
    XLA_FLAGS are a FATAL abort at backend init, so support must be probed in a
    throwaway child, never assumed. The verdict is cached per jaxlib version in
    the temp dir (one ~2s probe per container, not per pytest process)."""
    global _collective_flag_supported
    if _collective_flag_supported is not None:
        return _collective_flag_supported
    import tempfile

    try:
        import jaxlib

        version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:
        version = "unknown"
    cache = os.path.join(
        tempfile.gettempdir(), f"accelerate_tpu_xla_flag_probe_{version}"
    )
    try:
        with open(cache) as f:
            _collective_flag_supported = f.read().strip() == "1"
            return _collective_flag_supported
    except OSError:
        pass
    env = os.environ.copy()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _COLLECTIVE_TIMEOUT_FLAG
    probe = subprocess.run(
        [sys.executable, "-c", "import jax; jax.local_devices()"],
        env=env, capture_output=True, timeout=120,
    )
    _collective_flag_supported = probe.returncode == 0
    try:
        with open(cache, "w") as f:
            f.write("1" if _collective_flag_supported else "0")
    except OSError:
        pass
    return _collective_flag_supported


def cpu_mesh_env(num_devices: int = 8) -> dict:
    """Env for a child process running on the N-device virtual CPU mesh (the
    debug_launcher-adjacent single-process harness)."""
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # Hosts that inject a TPU PJRT plugin via sitecustomize (keyed on this var)
    # register it in EVERY child interpreter, where its tunnel client can abort
    # the process whenever another process holds the (single, serialized) chip.
    # CPU children must never load it.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # The caller's num_devices must WIN over an inherited device-count flag
    # (pytest's conftest bakes 8 into XLA_FLAGS; a 4-device request would
    # otherwise be silently ignored).
    from ..utils.environment import set_host_device_count_flag

    env["XLA_FLAGS"] = set_host_device_count_flag(env.get("XLA_FLAGS", ""), num_devices)
    # De-flake, not mask: all virtual devices share one intra-op thread pool, so
    # on a loaded small host a collective can take minutes to assemble its
    # participants — that's starvation, not a hang (XLA:CPU's default ~40s
    # rendezvous deadline calls it a hang and kills the child). Real hangs still
    # die at the harness subprocess timeout. NOTE: a longer deadline cannot fix
    # the second flake mechanism — the async-dispatch deadlock, where partitions
    # of DIFFERENT in-flight steps hold the pool's threads waiting on different
    # rendezvous; FusedTrainStep closes that one by fencing per call on the CPU
    # platform. Shrinking the thread pool likewise DEADLOCKS the first
    # cross-module collective (participants must run concurrently).
    # ... but only when the installed XLA build KNOWS the flag: parse_flags_from_env
    # aborts (SIGABRT at backend init) on unknown XLA_FLAGS entries, which turned
    # this de-flake into a deterministic child crash on older jaxlibs. Probed once
    # per jaxlib version (cached on disk) instead of guessed from version numbers.
    if "collective_call_terminate_timeout" not in env["XLA_FLAGS"]:
        if _supports_collective_timeout_flag():
            env["XLA_FLAGS"] += f" {_COLLECTIVE_TIMEOUT_FLAG}"
    # Children must resolve the package even when it's driven from a source checkout.
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    return env


def run_test_script(script_name: str, num_devices: int = 8, extra_args=()) -> subprocess.CompletedProcess:
    """Run one of the bundled `test_utils/scripts/` by name on the virtual CPU mesh."""
    from . import scripts

    script = os.path.join(os.path.dirname(scripts.__file__), script_name)
    return execute_subprocess([sys.executable, script, *extra_args], env=cpu_mesh_env(num_devices))

"""Pytest plumbing for the analysis trace guard: import (or `pytest_plugins`)
this module from a conftest and any test can assert "this train loop compiles
exactly N executables and never syncs":

    def test_loop_is_compile_stable(trace_guard):
        guard = trace_guard()           # record-mode TraceGuard
        warmup(step_fn)
        with guard:
            for batch in batches:
                step_fn(batch)
        assert_compiles(guard, exactly=0)

Lives in `test_utils` (not `tests/`) so launched scripts and downstream suites
get the same fixture post-install, exactly like the rest of test_utils.

Kept out of `test_utils/__init__` on purpose: this module imports pytest, and
test_utils is imported by launched training scripts that must not depend on it.
"""

from __future__ import annotations

import pytest

from ..analysis import TraceGuard


@pytest.fixture
def trace_guard():
    """Factory fixture: build record-mode TraceGuards (assertions stay in the
    test, so a failure reports through pytest instead of raising mid-loop).
    Pass on_violation="raise" to get the raising behavior instead."""

    def make(**kwargs) -> TraceGuard:
        kwargs.setdefault("on_violation", "record")
        return TraceGuard(**kwargs)

    return make


def assert_compiles(guard: TraceGuard, exactly: int = None, at_most: int = None):
    """Assert on a guard's compile ledger with a readable failure message
    (names every executable and its miss count)."""
    total = guard.total_recompiles
    detail = guard.report().summary()
    if exactly is not None:
        assert total == exactly, (
            f"expected exactly {exactly} compile(s) in the guarded window, saw {total} — {detail}"
        )
    if at_most is not None:
        assert total <= at_most, (
            f"expected at most {at_most} compile(s) in the guarded window, saw {total} — {detail}"
        )
    assert guard.host_transfers == 0, (
        f"guarded window made {guard.host_transfers} host transfer(s): "
        f"{guard.transfer_violations}"
    )

"""The "everything" end-to-end script (parity: reference test_utils/scripts/test_script.py,
804 LoC): process control, RNG sync, dataloader preparation (default + dispatch mode),
seedable-sampler determinism, `split_between_processes`, the trigger flag, and the core
`training_check` — distributed training must match a single-device baseline
loss-for-loss. Reused by the `accelerate-tpu test` CLI command."""

import os
import sys

import numpy as np

from accelerate_tpu.utils.operations import fetch_global


def init_state_check():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    state.print(f"State: {state!r}")
    assert state.num_processes >= 1
    assert state.num_devices >= 1
    return state


def process_execution_check(state):
    # on_main_process / ordering primitives must run and agree
    ran = {}

    @state.on_main_process
    def mark():
        ran["main"] = state.process_index

    mark()
    if state.is_main_process:
        assert ran["main"] == 0
    else:
        assert "main" not in ran
    with state.main_process_first():
        pass
    state.wait_for_everyone()


def split_between_processes_check(state):
    items = list(range(17))
    with state.split_between_processes(items) as mine:
        counts = state.num_processes
        base, extra = divmod(17, counts)
        expected_len = base + (1 if state.process_index < extra else 0)
        assert len(mine) == expected_len, (len(mine), expected_len)
    with state.split_between_processes(items, apply_padding=True) as mine:
        base, extra = divmod(17, state.num_processes)
        target = base + (1 if extra else 0)
        assert len(mine) == target
    with state.split_between_processes({"a": np.arange(8), "b": np.arange(8) * 2}) as mine:
        assert len(mine["a"]) == len(mine["b"])
    # nested-dict and tensor payloads (reference test_script.py:646-695): structure
    # splits recursively, arrays slice along dim 0 (padded to even shards).
    nested = {"outer": {"x": np.arange(16).reshape(16, 1), "y": list(range(16))}}
    with state.split_between_processes(nested) as mine:
        assert mine["outer"]["x"].shape[0] == len(mine["outer"]["y"])
    import jax.numpy as jnp

    with state.split_between_processes(jnp.arange(10), apply_padding=True) as mine:
        base, extra = divmod(10, state.num_processes)
        assert mine.shape[0] == base + (1 if extra else 0)


def rng_sync_check(state):
    from accelerate_tpu.utils.random import synchronize_rng_states

    np.random.seed(1000 + state.process_index)  # deliberately desynced
    synchronize_rng_states(["numpy"])
    draw = np.random.rand(3)
    from accelerate_tpu.utils import operations as ops

    gathered = ops.gather_object([draw.tolist()])
    for other in gathered:
        assert np.allclose(other, gathered[0]), "numpy RNG not synchronized across processes"
    state.wait_for_everyone()


def dl_preparation_check(state):
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader, prepare_data_loader

    n, bs = 64, 8
    data = [{"x": np.float32([i])} for i in range(n)]
    dl = SimpleDataLoader(data, BatchSampler(range(n), bs))
    prepared = prepare_data_loader(dl, use_seedable_sampler=False)
    seen = []
    for batch in prepared:
        # global array: the full batch is visible everywhere, but on true
        # multi-host topologies reading it requires the allgather-backed fetch.
        arr = fetch_global(batch["x"])
        seen.extend(arr[:, 0].tolist())
    assert sorted(int(v) for v in seen) == list(range(n)), "prepared loader lost/duplicated samples"

    # split_batches: global batch == inner batch size
    prepared = prepare_data_loader(dl, split_batches=True, use_seedable_sampler=False)
    for batch in prepared:
        assert batch["x"].shape[0] == bs  # shape is global metadata; no fetch needed
        break
    state.wait_for_everyone()


def central_dl_preparation_check(state):
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader, prepare_data_loader

    n, bs = 32, 4
    data = [{"x": np.float32([i])} for i in range(n)]
    dl = SimpleDataLoader(data, BatchSampler(range(n), bs))
    prepared = prepare_data_loader(dl, dispatch_batches=True, use_seedable_sampler=False)
    seen = []
    for batch in prepared:
        seen.extend(fetch_global(batch["x"])[:, 0].tolist())
    assert sorted(int(v) for v in seen) == list(range(n)), "dispatch loader lost/duplicated samples"
    state.wait_for_everyone()


def seedable_sampler_check(state):
    from accelerate_tpu.data_loader import (
        BatchSampler,
        SeedableRandomSampler,
        SimpleDataLoader,
        prepare_data_loader,
    )

    n, bs = 32, 4
    data = [{"x": np.float32([i])} for i in range(n)]

    def epoch_order(seed):
        sampler = SeedableRandomSampler(num_samples=n, seed=seed)
        dl = SimpleDataLoader(data, BatchSampler(sampler, bs))
        prepared = prepare_data_loader(dl, use_seedable_sampler=True, data_seed=seed)
        order = []
        for batch in prepared:
            order.extend(fetch_global(batch["x"])[:, 0].astype(int).tolist())
        return order

    assert epoch_order(42) == epoch_order(42), "seedable sampler not deterministic"
    assert epoch_order(42) != epoch_order(7), "seedable sampler ignores the seed"
    state.wait_for_everyone()


def training_check(state):
    import jax
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=64, seed=5)
    data = [dataset[i] for i in range(len(dataset))]

    # single-device baseline (plain optax loop on the host)
    import jax.numpy as jnp

    model = RegressionModel()
    tx = optax.sgd(0.1)
    params = model.params
    opt_state = tx.init(params)
    baseline_losses = []
    for epoch in range(3):
        for start in range(0, 64, 16):
            xs = np.stack([data[i]["x"] for i in range(start, start + 16)])
            ys = np.stack([data[i]["y"] for i in range(start, start + 16)])
            batch = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}

            def loss_fn(p):
                pred = model.apply_fn(p, batch["x"])
                return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            baseline_losses.append(loss)  # device-side; read once after the loop
    baseline_losses = [float(l) for l in baseline_losses]

    # framework run (sharded over whatever topology this script landed on).
    # split_batches makes the GLOBAL batch process-count invariant, so the loss
    # trajectory matches the single-device baseline at any num_processes.
    accelerator = Accelerator(split_batches=True)
    fw_model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(64), 16))
    pmodel, popt, pdl = accelerator.prepare(fw_model, optax.sgd(0.1), dl)
    fw_losses = []
    for epoch in range(3):
        for batch in pdl:
            loss = accelerator.backward(pmodel.loss, batch)
            popt.step()
            popt.zero_grad()
            fw_losses.append(loss)  # device-side; read once after the loop
    fw_losses = [float(l) for l in fw_losses]

    assert len(fw_losses) == len(baseline_losses)
    np.testing.assert_allclose(np.array(fw_losses), np.array(baseline_losses), rtol=1e-4, atol=1e-5)
    state.print("training_check: distributed == single-device, loss-for-loss ✓")

    AcceleratorState._reset_state()
    GradientState._reset_state()


def training_variants_check(state):
    """Loss-parity for the prepare() variants the reference exercises in
    training_check (test_script.py:420+): split_batches, bf16 autocast, and
    gradient accumulation — each against the same plain-optax baseline."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=64, seed=5)
    data = [dataset[i] for i in range(len(dataset))]

    def baseline(batch_size):
        model = RegressionModel()
        tx = optax.sgd(0.1)
        params = model.params
        opt_state = tx.init(params)
        losses = []
        for start in range(0, 64, batch_size):
            xs = np.stack([data[i]["x"] for i in range(start, start + batch_size)])
            ys = np.stack([data[i]["y"] for i in range(start, start + batch_size)])

            def loss_fn(p):
                pred = model.apply_fn(p, jnp.asarray(xs))
                return jnp.mean((pred[:, 0] - jnp.asarray(ys)) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(loss)  # device-side; read once after the loop
        return [float(l) for l in losses]

    def framework(batch_size, **acc_kwargs):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        # Global-batch invariance across process counts (same rationale as
        # training_check); explicit split_batches tests still override it.
        acc_kwargs.setdefault("split_batches", True)
        accelerator = Accelerator(**acc_kwargs)
        dl = SimpleDataLoader(data, BatchSampler(range(64), batch_size))
        pmodel, popt, pdl = accelerator.prepare(RegressionModel(), optax.sgd(0.1), dl)
        losses = []
        for batch in pdl:
            with accelerator.accumulate(pmodel):
                losses.append(float(accelerator.backward(pmodel.loss, batch)))
                popt.step()
                popt.zero_grad()
        return losses

    np.testing.assert_allclose(framework(16, split_batches=True), baseline(16), rtol=1e-4, atol=1e-5)
    # bf16 autocast: same convergence at reduced precision (loose tolerance)
    np.testing.assert_allclose(framework(16, mixed_precision="bf16"), baseline(16), rtol=0.1, atol=0.05)
    # accumulation 2 over half-size batches == big-batch baseline: both microbatch
    # losses are computed at the SAME params, so their mean equals the big-batch loss.
    accum = np.asarray(framework(8, gradient_accumulation_steps=2))
    np.testing.assert_allclose((accum[0::2] + accum[1::2]) / 2, baseline(16), rtol=1e-3, atol=1e-4)
    AcceleratorState._reset_state()
    GradientState._reset_state()
    state.print("training_variants: split_batches / bf16 / accumulation ✓")


def resume_check(state):
    """skip_first_batches mid-epoch resume determinism (reference data_loader.py:1082)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.state import AcceleratorState, GradientState

    n, bs = 32, 4
    data = [{"x": np.float32([i])} for i in range(n)]
    accelerator = Accelerator()
    dl = SimpleDataLoader(data, BatchSampler(range(n), bs))
    pdl = accelerator.prepare_data_loader(dl)
    full = [fetch_global(b["x"])[:, 0].tolist() for b in pdl]
    resumed = [fetch_global(b["x"])[:, 0].tolist() for b in accelerator.skip_first_batches(pdl, 3)]
    assert resumed == full[3:], (resumed, full[3:])
    AcceleratorState._reset_state()
    GradientState._reset_state()
    state.print("resume (skip_first_batches) ✓")


def gather_for_metrics_check(state):
    """Uneven tail: the duplicated pad samples must be dropped (reference
    accelerator.py:2331-2396), plus the object plane the reference can't do on XLA."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.state import AcceleratorState, GradientState

    n = 19  # not divisible by the batch
    data = [{"x": np.float32([i])} for i in range(n)]
    accelerator = Accelerator()
    dl = SimpleDataLoader(data, BatchSampler(range(n), 8, drop_last=False))
    pdl = accelerator.prepare_data_loader(dl)
    seen = []
    for batch in pdl:
        seen.append(np.asarray(accelerator.gather_for_metrics(batch["x"]))[:, 0])
    seen = np.concatenate(seen)
    assert seen.shape[0] == n, (seen.shape, n)
    assert sorted(int(v) for v in seen) == list(range(n))

    objs = accelerator.gather_for_metrics([f"rank{state.process_index}"], use_gather_object=True)
    assert objs == [f"rank{i}" for i in range(state.num_processes)], objs
    AcceleratorState._reset_state()
    GradientState._reset_state()
    state.print("gather_for_metrics: remainder truncation + object plane ✓")


def reinstantiated_state_check(state):
    """Borg contract (reference test_script.py:713-728): constructing PartialState
    again yields the SAME topology/state; AcceleratorState layered on top shares it."""
    from accelerate_tpu.state import AcceleratorState, PartialState

    again = PartialState()
    assert again.process_index == state.process_index
    assert again.num_processes == state.num_processes
    acc_state = AcceleratorState()
    assert acc_state.process_index == state.process_index
    state.wait_for_everyone()


def seedable_sampler_in_shard_check(state):
    """Seedable shuffle composed with BatchSamplerShard (reference
    test_script.py:383-401): every process sees the same epoch permutation, and the
    union of per-process index batches covers the dataset exactly once."""
    from accelerate_tpu.data_loader import BatchSampler, BatchSamplerShard, SeedableRandomSampler

    n = 24
    sampler = SeedableRandomSampler(num_samples=n, seed=7)
    sampler.set_epoch(3)
    shard = BatchSamplerShard(
        BatchSampler(sampler, batch_size=4),
        num_processes=state.num_processes,
        process_index=state.process_index,
    )
    local = [i for batch in shard for i in batch]
    from accelerate_tpu.utils import operations as ops

    all_indices = ops.gather_object(local)
    # even_batches padding may loop early samples when num_processes doesn't
    # divide the batch count, so the robust claim is SET coverage: every sample
    # appears at least once and nothing out of range appears.
    assert set(all_indices) == set(range(n)), "sharded seedable sampler must cover the dataset"
    assert len(all_indices) >= n
    # Same seed+epoch => identical permutation on EVERY process: gather each
    # rank's full local walk and compare against rank 0's.
    sampler2 = SeedableRandomSampler(num_samples=n, seed=7)
    sampler2.set_epoch(3)
    walks = ops.gather_object([list(sampler2)])
    assert all(w == walks[0] for w in walks), "seedable permutation differs across processes"
    state.wait_for_everyone()


def sync_module_states_check(state):
    """FSDP sync_module_states: rank-divergent initial weights must come out of
    prepare() identical everywhere (rank 0 wins) — and with the knob off they
    must stay divergent (proves the broadcast is the knob's doing)."""
    import jax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import RegressionModel
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin
    from accelerate_tpu.utils.operations import fetch_global, gather_object

    if state.num_processes == 1:
        return

    def first_leaf_value(prepared):
        leaf = jax.tree_util.tree_leaves(prepared.params)[0]
        return float(np.asarray(fetch_global(leaf)).reshape(-1)[0])

    for sync, expect_equal in ((True, True), (False, False)):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        accelerator = Accelerator(
            fsdp_plugin=FullyShardedDataParallelPlugin(sync_module_states=sync)
        )
        model = RegressionModel(a=float(state.process_index), b=1.0)  # divergent init
        prepared = accelerator.prepare(model)
        values = gather_object([first_leaf_value(prepared)])
        equal = all(v == values[0] for v in values)
        assert equal == expect_equal, (
            f"sync_module_states={sync}: expected equal={expect_equal}, got {values}"
        )
    state.print("sync_module_states_check: rank-0 weights win when on, stay local when off ✓")
    AcceleratorState._reset_state()
    GradientState._reset_state()


def trigger_check(state):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState

    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    if state.process_index == state.num_processes - 1:
        accelerator.set_trigger()
    assert accelerator.check_trigger(), "trigger set on one process must be visible everywhere"
    assert not accelerator.check_trigger(), "trigger must reset after firing"
    AcceleratorState._reset_state()
    GradientState._reset_state()


def main():
    state = init_state_check()
    state.print("**Process control**")
    process_execution_check(state)
    split_between_processes_check(state)
    state.print("**RNG sync**")
    rng_sync_check(state)
    state.print("**DataLoader preparation**")
    dl_preparation_check(state)
    central_dl_preparation_check(state)
    seedable_sampler_check(state)
    state.print("**Training check**")
    training_check(state)
    training_variants_check(state)
    state.print("**Resume / metrics**")
    resume_check(state)
    gather_for_metrics_check(state)
    state.print("**Trigger**")
    trigger_check(state)
    state.print("**FSDP sync_module_states**")
    sync_module_states_check(state)
    state.print("**State reinstantiation / sharded sampler**")
    reinstantiated_state_check(state)
    seedable_sampler_in_shard_check(state)
    state.print("All checks passed.")


if __name__ == "__main__":
    main()

"""Collective-op correctness script (parity: reference test_utils/scripts/test_ops.py,
179 LoC): gather / gather_object / broadcast / broadcast_object_list / reduce /
pad_across_processes over the device and object planes, plus the debug-mode shape
verifier raising `DistributedOperationException` on rank-divergent shapes."""

import numpy as np


def gather_check(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils import operations as ops

    local = jnp.arange(4, dtype=jnp.float32) + 10 * state.process_index
    gathered = np.asarray(ops.gather(local))
    assert gathered.shape[0] >= 4
    if state.num_processes == 1:
        np.testing.assert_allclose(gathered, np.arange(4, dtype=np.float32))
    state.wait_for_everyone()
    print("gather ✓")


def gather_object_check(state):
    from accelerate_tpu.utils import operations as ops

    # NB: the reference raises NotImplementedError for this on XLA (operations.py:462);
    # the object plane here rides the coordination service instead.
    result = ops.gather_object([f"rank-{state.process_index}"])
    assert result == [f"rank-{i}" for i in range(state.num_processes)], result
    print("gather_object ✓")


def broadcast_check(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils import operations as ops

    value = jnp.full((3,), float(state.process_index), dtype=jnp.float32)
    out = np.asarray(ops.broadcast(value, from_process=0))
    np.testing.assert_allclose(out, np.zeros(3, dtype=np.float32))

    objs = [state.process_index, {"rank": state.process_index}]
    objs = ops.broadcast_object_list(objs, from_process=0)
    assert objs[0] == 0 and objs[1] == {"rank": 0}
    print("broadcast ✓")


def reduce_check(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils import operations as ops

    one = jnp.ones((2,), dtype=jnp.float32)
    summed = np.asarray(ops.reduce(one, reduction="sum"))
    np.testing.assert_allclose(summed, np.full(2, float(state.num_processes)))
    mean = np.asarray(ops.reduce(one, reduction="mean"))
    np.testing.assert_allclose(mean, np.ones(2))
    print("reduce ✓")


def pad_check(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils import operations as ops

    local = jnp.ones((2 + state.process_index, 3), dtype=jnp.float32)
    padded = np.asarray(ops.pad_across_processes(local, dim=0))
    expected_rows = 2 + state.num_processes - 1
    assert padded.shape[0] == expected_rows, (padded.shape, expected_rows)
    print("pad_across_processes ✓")


def debug_mode_check(state):
    from accelerate_tpu.utils import operations as ops
    from accelerate_tpu.utils.operations import DistributedOperationException

    if state.num_processes == 1:
        print("debug_mode: skipped (single process)")
        return
    import jax.numpy as jnp

    state.debug = True
    try:
        # rank-divergent shapes: the verifier must catch this before the collective hangs
        bad = jnp.ones((2 + state.process_index,), dtype=jnp.float32)
        try:
            ops.gather(bad)
        except DistributedOperationException:
            print("debug_mode ✓")
        else:
            raise AssertionError("debug mode failed to flag mismatched shapes")
    finally:
        state.debug = False


def main():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    gather_check(state)
    gather_object_check(state)
    broadcast_check(state)
    reduce_check(state)
    pad_check(state)
    debug_mode_check(state)
    print("All op checks passed.")


if __name__ == "__main__":
    main()

"""Launched correctness scripts (parity: reference test_utils/scripts/ — test_script.py,
test_sync.py, test_ops.py). Each has a `main()` so it can run as `python <script>` on
any topology (single chip, the 8-device virtual CPU mesh, a pod slice) or be handed to
`debug_launcher` for real multi-process coverage."""

"""Launched integration gate: accuracy floor + peak-memory ceiling per strategy.

Parity: the reference gates every strategy on launched end-to-end quality —
eval accuracy >= `--performance_lower_bound` (0.82 pattern,
`test_utils/scripts/external_deps/test_performance.py:199-202`,
`tests/fsdp/test_fsdp.py:214`) and peak memory <= an upper bound
(`external_deps/test_peak_memory_usage.py`, `tests/fsdp/test_fsdp.py:313-349`).

Two zero-egress tasks (no network — parity for the reference's MRPC download,
`test_utils/training.py:64`, `tests/test_samples/MRPC`):

- `text_pair` (default, reference-grade): paraphrase detection over the
  committed CSV fixture (`tests/test_samples/text_pair`). A from-scratch
  bert-tiny must learn a slot-wise synonym-matching circuit to clear 0.82 dev
  accuracy — a 10x-wrong LR never leaves the ln(2) saddle, a subtly broken
  grad path caps below the floor (the mutation audit in
  tests/test_integration_gates.py proves the floor binds).
- `token_parity` (fast tier): the label is the parity of the first token id,
  learnable in a few steps — checks the stack end-to-end, not training quality.

Run via `accelerate-tpu launch` (tests/test_integration_gates.py) or directly:

    python -m accelerate_tpu.test_utils.scripts.test_performance \
        --strategy full_shard --performance_lower_bound 0.82
"""

import argparse
import json
import os
import sys

import numpy as np


def make_dataset(n: int, seq_len: int, vocab: int, seed: int):
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, vocab, size=(n, seq_len)).astype(np.int32)
    # The label-carrying first token is drawn from a small id set shared by train
    # and eval, so the gate tests that training WORKS (the pooler reads position 0),
    # not whether embeddings of never-seen ids generalize.
    ids[:, 0] = rng.integers(2, 18, size=(n,))
    labels = (ids[:, 0] % 2).astype(np.int64)
    return [{"input_ids": ids[i], "labels": labels[i]} for i in range(n)]


def find_text_pair_dir() -> str:
    """Locate the committed fixture: explicit flag/env first, then the source
    checkout layout relative to this file."""
    env = os.environ.get("ACCELERATE_TPU_TEST_SAMPLES")
    if env:
        return env
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    cand = os.path.join(repo, "tests", "test_samples", "text_pair")
    if os.path.isdir(cand):
        return cand
    raise FileNotFoundError(
        "text_pair fixture not found; pass --data_dir or set ACCELERATE_TPU_TEST_SAMPLES"
    )


def load_text_pair(data_dir: str, split: str, seq_len: int = 16):
    """CSV rows -> {input_ids, token_type_ids, labels} dicts ([CLS] a [SEP] b [SEP])."""
    import csv

    with open(os.path.join(data_dir, "vocab.txt")) as f:
        vocab = {w.strip(): i for i, w in enumerate(f)}
    cls_id, sep_id = vocab["[CLS]"], vocab["[SEP]"]
    rows = []
    with open(os.path.join(data_dir, f"{split}.csv"), newline="") as f:
        for r in csv.DictReader(f):
            a = [vocab[w] for w in r["sentence1"].split()]
            b = [vocab[w] for w in r["sentence2"].split()]
            toks = [cls_id, *a, sep_id, *b, sep_id]
            ids = np.zeros(seq_len, np.int32)
            types = np.zeros(seq_len, np.int32)
            ids[: len(toks)] = toks
            types[len(a) + 2 : len(toks)] = 1
            rows.append(
                {"input_ids": ids, "token_type_ids": types, "labels": np.int64(int(r["label"]))}
            )
    return rows


def build_accelerator(strategy: str, mixed_precision: str):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    if strategy == "dp":
        return Accelerator(mixed_precision=mixed_precision)
    plugin_kwargs = {
        "full_shard": dict(sharding_strategy="FULL_SHARD"),
        "shard_grad_op": dict(sharding_strategy="SHARD_GRAD_OP"),
        "offload": dict(sharding_strategy="FULL_SHARD", offload_optimizer_state=True),
    }[strategy]
    return Accelerator(
        mixed_precision=mixed_precision,
        fsdp_plugin=FullyShardedDataParallelPlugin(min_num_params=1024, **plugin_kwargs),
    )


def peak_memory_mb() -> float | None:
    """Per-device peak bytes from the backend, if it reports them (TPU does; the
    host-CPU test platform usually doesn't)."""
    import jax

    stats = jax.local_devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    return peak / (1024 * 1024) if peak else None


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--strategy", default="dp", choices=["dp", "full_shard", "shard_grad_op", "offload"])
    parser.add_argument("--task", default="text_pair", choices=["text_pair", "token_parity"])
    parser.add_argument("--performance_lower_bound", type=float, default=0.82)
    parser.add_argument("--peak_memory_upper_bound_mb", type=float, default=None)
    parser.add_argument("--mixed_precision", default="bf16")
    parser.add_argument("--epochs", type=int, default=None, help="default: 14 text_pair, 10 token_parity")
    parser.add_argument("--lr", type=float, default=None, help="default: 3e-4 text_pair, 1e-3 token_parity")
    parser.add_argument("--batch_size", type=int, default=32, help="global batch size")
    parser.add_argument("--seq_len", type=int, default=None)
    parser.add_argument("--data_dir", default=None, help="text_pair fixture dir (default: auto-discover)")
    parser.add_argument("--train_size", type=int, default=256, help="token_parity only")
    parser.add_argument("--eval_size", type=int, default=96, help="token_parity only")
    args = parser.parse_args(argv)

    import jax
    import optax

    from accelerate_tpu import SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
    from accelerate_tpu.models import bert_tiny, create_bert_model
    from accelerate_tpu.utils.random import set_seed

    set_seed(42)
    accelerator = build_accelerator(args.strategy, args.mixed_precision)

    cfg = bert_tiny()
    if args.task == "text_pair":
        # Calibrated recipe (MEASUREMENTS_r04.md): from-scratch bert-tiny crosses
        # dev 0.87 at epoch 8 and ~0.93 at 11 with adamw(3e-4, wd 0.01), global
        # batch 32, seeded reshuffle; 14 epochs leaves margin over the 0.82 floor.
        args.seq_len = args.seq_len or 16
        args.epochs = args.epochs or 14
        args.lr = args.lr or 3e-4
        data_dir = args.data_dir or find_text_pair_dir()
        train_data = load_text_pair(data_dir, "train", args.seq_len)
        eval_data = load_text_pair(data_dir, "dev", args.seq_len)
        tx = optax.adamw(args.lr, weight_decay=0.01)
        # Seeded reshuffle each epoch (DataLoaderShard advances the sampler epoch).
        train_sampler = SeedableRandomSampler(train_data, seed=7)
    else:
        args.seq_len = args.seq_len or 32
        args.epochs = args.epochs or 10
        args.lr = args.lr or 1e-3
        train_data = make_dataset(args.train_size, args.seq_len, cfg.vocab_size, seed=0)
        # Deliberately NOT a multiple of the batch size: the last eval batch is
        # padded by the loader and gather_for_metrics must truncate the duplicates.
        eval_data = make_dataset(args.eval_size - 5, args.seq_len, cfg.vocab_size, seed=1)
        tx = optax.adamw(args.lr)
        train_sampler = range(len(train_data))

    model = create_bert_model(cfg, seq_len=args.seq_len)
    train_dl = SimpleDataLoader(train_data, BatchSampler(train_sampler, args.batch_size, drop_last=True))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size, drop_last=False))

    pmodel, popt, ptrain_dl, peval_dl = accelerator.prepare(model, tx, train_dl, eval_dl)

    step_fn = accelerator.train_step()
    loss = None
    for _ in range(args.epochs):
        for batch in ptrain_dl:
            loss = step_fn(batch)
    final_loss = float(loss)

    hits = []
    for batch in peval_dl:
        logits = pmodel.eval_apply(batch["input_ids"], token_type_ids=batch.get("token_type_ids"))
        pred = logits.argmax(-1)
        pred, labels = accelerator.gather_for_metrics((pred, batch["labels"]))
        hits.append(np.asarray(pred) == np.asarray(labels))
    hits = np.concatenate(hits)
    assert hits.shape[0] == len(eval_data), (
        f"gather_for_metrics returned {hits.shape[0]} samples, expected {len(eval_data)} "
        f"(padding not truncated)"
    )
    accuracy = float(hits.mean())

    peak_mb = peak_memory_mb()
    result = {
        "strategy": args.strategy,
        "task": args.task,
        "accuracy": accuracy,
        "final_loss": final_loss,
        "peak_memory_mb": peak_mb,
        "n_devices": jax.device_count(),
    }
    accelerator.print(json.dumps(result))

    assert accuracy >= args.performance_lower_bound, (
        f"accuracy gate FAILED for {args.strategy}: {accuracy:.4f} < {args.performance_lower_bound}"
    )
    if args.peak_memory_upper_bound_mb is not None and peak_mb is not None:
        assert peak_mb <= args.peak_memory_upper_bound_mb, (
            f"peak-memory gate FAILED for {args.strategy}: {peak_mb:.1f}MB > "
            f"{args.peak_memory_upper_bound_mb}MB"
        )
    accelerator.print(f"Performance gate passed: {args.strategy} accuracy={accuracy:.4f}")
    return result


if __name__ == "__main__":
    main()
    sys.exit(0)

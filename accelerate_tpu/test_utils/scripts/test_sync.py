"""Gradient accumulation / sync semantics script (parity: reference
test_utils/scripts/test_sync.py, 392 LoC): accumulated microbatch training must equal
big-batch training for linear models; `sync_gradients` must flip exactly at
accumulation boundaries and at end-of-dataloader."""

import numpy as np


def _fresh_accelerator(**kwargs):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def accumulation_equivalence_check():
    import jax
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
    from accelerate_tpu.utils import GradientAccumulationPlugin

    dataset = RegressionDataset(length=64, seed=11)
    data = [dataset[i] for i in range(len(dataset))]

    def run(accum, batch_size):
        accelerator = _fresh_accelerator(
            gradient_accumulation_plugin=GradientAccumulationPlugin(
                num_steps=accum, sync_with_dataloader=False
            )
        )
        model = RegressionModel()
        dl = SimpleDataLoader(data, BatchSampler(range(64), batch_size))
        pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
        for batch in pdl:
            with accelerator.accumulate(pmodel):
                accelerator.backward(pmodel.loss, batch)
                popt.step()
                popt.zero_grad()
        return pmodel.params

    params_accum = run(accum=4, batch_size=8)
    params_big = run(accum=1, batch_size=32)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(params_accum), jax.tree_util.tree_leaves(params_big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
    print("accumulation_equivalence ✓")


def sync_flag_check():
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=32, seed=3)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=2)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)
    flags = []
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            accelerator.backward(pmodel.loss, batch)
            flags.append(accelerator.sync_gradients)
            popt.step()
            popt.zero_grad()
    assert flags == [False, True, False, True], flags
    print("sync_flag ✓")


def end_of_dataloader_check():
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=24, seed=3)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=4)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(24), 8))  # 3 batches < accum 4
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)
    flags = []
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            accelerator.backward(pmodel.loss, batch)
            flags.append(accelerator.sync_gradients)
            popt.step()
            popt.zero_grad()
    assert flags[-1] is True, "end of dataloader must force a sync step"
    print("end_of_dataloader ✓")


def main():
    accumulation_equivalence_check()
    sync_flag_check()
    end_of_dataloader_check()
    print("All sync checks passed.")


if __name__ == "__main__":
    main()

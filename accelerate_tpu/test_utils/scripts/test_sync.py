"""Gradient accumulation / sync semantics script (parity: reference
test_utils/scripts/test_sync.py, 392 LoC): accumulated microbatch training must equal
big-batch training for linear models; `sync_gradients` must flip exactly at
accumulation boundaries and at end-of-dataloader."""

import numpy as np

from accelerate_tpu.utils.operations import fetch_global


def _fresh_accelerator(**kwargs):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    # Global-batch invariance across process counts: every check's step count and
    # loss values must not depend on how many coordinated processes run this
    # script (the multi-process leg of `accelerate-tpu test`).
    kwargs.setdefault("split_batches", True)
    return Accelerator(**kwargs)


def accumulation_equivalence_check():
    import jax
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
    from accelerate_tpu.utils import GradientAccumulationPlugin

    dataset = RegressionDataset(length=64, seed=11)
    data = [dataset[i] for i in range(len(dataset))]

    def run(accum, batch_size):
        accelerator = _fresh_accelerator(
            gradient_accumulation_plugin=GradientAccumulationPlugin(
                num_steps=accum, sync_with_dataloader=False
            )
        )
        model = RegressionModel()
        dl = SimpleDataLoader(data, BatchSampler(range(64), batch_size))
        pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
        for batch in pdl:
            with accelerator.accumulate(pmodel):
                accelerator.backward(pmodel.loss, batch)
                popt.step()
                popt.zero_grad()
        return pmodel.params

    params_accum = run(accum=4, batch_size=8)
    params_big = run(accum=1, batch_size=32)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(params_accum), jax.tree_util.tree_leaves(params_big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
    print("accumulation_equivalence ✓")


def sync_flag_check():
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=32, seed=3)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=2)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)
    flags = []
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            accelerator.backward(pmodel.loss, batch)
            flags.append(accelerator.sync_gradients)
            popt.step()
            popt.zero_grad()
    assert flags == [False, True, False, True], flags
    print("sync_flag ✓")


def end_of_dataloader_check():
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=24, seed=3)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=4)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(24), 8))  # 3 batches < accum 4
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)
    flags = []
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            accelerator.backward(pmodel.loss, batch)
            flags.append(accelerator.sync_gradients)
            popt.step()
            popt.zero_grad()
    assert flags[-1] is True, "end of dataloader must force a sync step"
    print("end_of_dataloader ✓")


def grad_equality_at_boundaries_check():
    """Reference test_sync.py:113-305 asserts grads are equal/unequal across ranks at
    exactly the right steps. Under GSPMD grads are one logical array, so the TPU-native
    contract is: accumulated grads equal the SUM of the microbatch grads at the
    boundary, and params move ONLY at boundary steps."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=32, seed=13)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=2)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)

    # Independent per-microbatch grads at the CURRENT params, for comparison
    # (fetch_global: batches/params are global arrays on multi-process runs).
    def manual_grad(params, batch):
        def loss_fn(p):
            pred = pmodel._mp_apply(p, fetch_global(batch["x"]))
            return jnp.mean((pred[:, 0] - jnp.asarray(fetch_global(batch["y"]))) ** 2)

        return jax.grad(loss_fn)(params)

    batches = list(pdl)
    params_before = jax.tree_util.tree_map(fetch_global, pmodel.params)
    expected = None
    for i, batch in enumerate(batches):
        with accelerator.accumulate(pmodel):
            g = manual_grad(pmodel.params, batch)
            # loss is scaled by 1/accum inside backward; mirror that
            g = jax.tree_util.tree_map(lambda x: x / 2.0, g)
            expected = g if expected is None else jax.tree_util.tree_map(jnp.add, expected, g)
            accelerator.backward(pmodel.loss, batch)
            if accelerator.sync_gradients:
                acc_grads = popt._grads
                for a, b in zip(jax.tree_util.tree_leaves(acc_grads), jax.tree_util.tree_leaves(expected)):
                    np.testing.assert_allclose(fetch_global(a), fetch_global(b), rtol=1e-4, atol=1e-6)
                expected = None
            popt.step()
            popt.zero_grad()
        params_now = jax.tree_util.tree_map(fetch_global, pmodel.params)
        moved = any(
            not np.allclose(a, b)
            for a, b in zip(jax.tree_util.tree_leaves(params_before), jax.tree_util.tree_leaves(params_now))
        )
        boundary = i % 2 == 1
        assert moved == boundary, f"params {'moved' if moved else 'frozen'} at step {i} (boundary={boundary})"
        params_before = params_now
    print("grad_equality_at_boundaries ✓")


def no_sync_check():
    """accelerator.no_sync(): grads accumulate without stepping, exactly like the
    reference's DDP no_sync contract (accelerator.py:909-948)."""
    import jax
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=16, seed=2)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator()
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.1), dl)
    batches = list(pdl)
    before = jax.tree_util.tree_map(np.asarray, pmodel.params)
    with accelerator.no_sync(pmodel):
        accelerator.backward(pmodel.loss, batches[0])
        popt.step()
        popt.zero_grad()
    after_nosync = jax.tree_util.tree_map(np.asarray, pmodel.params)
    for a, b in zip(jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after_nosync)):
        np.testing.assert_array_equal(a, b)
    assert popt._grads is not None, "no_sync must keep the accumulated grads"
    accelerator.backward(pmodel.loss, batches[1])
    popt.step()
    popt.zero_grad()
    after_sync = jax.tree_util.tree_map(np.asarray, pmodel.params)
    moved = any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after_sync))
    )
    assert moved, "step after no_sync must apply the accumulated update"
    print("no_sync ✓")


def scheduler_step_check():
    """AcceleratedScheduler steps only when the optimizer really stepped
    (reference scheduler.py:54-82)."""
    import optax

    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    dataset = RegressionDataset(length=32, seed=4)
    data = [dataset[i] for i in range(len(dataset))]
    accelerator = _fresh_accelerator(gradient_accumulation_steps=2)
    model = RegressionModel()
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
    schedule = optax.linear_schedule(init_value=0.1, end_value=0.0, transition_steps=10)
    pmodel, popt, psched, pdl = accelerator.prepare(model, optax.sgd(0.1), schedule, dl)
    counts = []
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            accelerator.backward(pmodel.loss, batch)
            popt.step()
            psched.step()
            popt.zero_grad()
            counts.append(psched._step_count)
    # 4 batches, accumulation 2 -> the schedule advances on steps 2 and 4 only.
    assert counts == [0, 1, 1, 2], counts
    print("scheduler_step ✓")


def main():
    accumulation_equivalence_check()
    sync_flag_check()
    end_of_dataloader_check()
    grad_equality_at_boundaries_check()
    no_sync_check()
    scheduler_step_check()
    print("All sync checks passed.")


if __name__ == "__main__":
    main()

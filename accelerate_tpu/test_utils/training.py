"""Toy training fixtures (parity: reference test_utils/training.py:22-62 —
RegressionDataset / RegressionModel, the y = 2x + 3 strategy used by every launched
correctness script)."""

from __future__ import annotations

import numpy as np


class RegressionDataset:
    """y = a*x + b with small noise (reference training.py:22-40)."""

    def __init__(self, a=2, b=3, length=64, seed=0):
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + 0.1 * rng.normal(size=(length,))).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {"x": self.x[i : i + 1], "y": self.y[i]}


def regression_loss(params, batch, apply_fn):
    import jax.numpy as jnp

    pred = apply_fn(params, batch["x"])
    return jnp.mean((pred[:, 0] - batch["y"]) ** 2)


def RegressionModel(a=0.0, b=0.0):
    """A one-parameter-pair linear model as a Model bundle (reference training.py:42-62).

    Initialized at (a, b) so launched scripts can start all ranks identically without
    relying on seed plumbing.
    """
    import jax.numpy as jnp

    from ..modeling import Model

    params = {"a": jnp.asarray([float(a)]), "b": jnp.asarray([float(b)])}

    def apply_fn(p, x):
        return x * p["a"] + p["b"]

    def loss_fn(p, batch, apply_fn_):
        pred = apply_fn_(p, batch["x"])
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    return Model.from_fn(apply_fn, params, loss_fn=loss_fn)


def RegressionMLPModel(hidden=64, seed=0):
    """The same y = 2x + 3 regression as a small MLP bundle — kernels big
    enough (hidden x hidden >= the planner's ZeRO size floor) and cleanly
    divisible by a ("data", "model") mesh, so a chaos/2D-training workload can
    exercise `sharding_rules="auto"` end to end: model-sharded kernels plus
    data-sharded Adam moments."""
    import jax.numpy as jnp
    import numpy as np

    from ..modeling import Model

    rng = np.random.default_rng(seed)
    s = lambda *shape: jnp.asarray(rng.normal(scale=0.1, size=shape).astype(np.float32))
    params = {
        "dense_in": {"kernel": s(1, hidden), "bias": s(hidden)},
        "dense_mid": {"kernel": s(hidden, hidden), "bias": s(hidden)},
        "dense_out": {"kernel": s(hidden, 1), "bias": s(1)},
    }

    def apply_fn(p, x):
        h = jnp.maximum(x @ p["dense_in"]["kernel"] + p["dense_in"]["bias"], 0.0)
        h = jnp.maximum(h @ p["dense_mid"]["kernel"] + p["dense_mid"]["bias"], 0.0)
        return h @ p["dense_out"]["kernel"] + p["dense_out"]["bias"]

    def loss_fn(p, batch, apply_fn_):
        pred = apply_fn_(p, batch["x"])
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    return Model.from_fn(apply_fn, params, loss_fn=loss_fn)

"""Pipeline-parallel inference (reference inference.py — PiPPy integration).

The reference fx-traces the model into stages (`Pipe.from_tracing`, reference
inference.py:168-172), places one stage per rank, and moves activations with c10d
send/recv; batches are chunked and padded (`pad_input_tensors`, reference
inference.py:101-123). Here the same user surface sits on the TPU-native pipeline
(parallel/pipeline.py): stages live on the "stage" mesh axis, activation hops are
`lax.ppermute` over ICI inside one jitted SPMD program, and "tracing" is replaced by the
`LayeredApply` stage decomposition the model families ship.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import numpy as np

from .state import AcceleratorState
from .utils.operations import pad_input_tensors


class PipelineInferencer:
    """Callable wrapper: pads + chunks the batch, runs the pipelined forward, and
    truncates the padding back off (reference `pippy_forward` inference.py:96-123)."""

    def __init__(self, pipelined, mesh, num_microbatches: int):
        self.pipelined = pipelined
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self._divisor = (
            mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1) * num_microbatches
        )

    def __call__(self, batch):
        import jax

        leaves = jax.tree_util.tree_leaves(batch)
        if not leaves:
            raise ValueError("Empty batch")
        n = leaves[0].shape[0]
        padded_n = math.ceil(n / self._divisor) * self._divisor
        if padded_n != n:
            batch = pad_input_tensors(batch, n, self._divisor)
        out = self.pipelined(batch)
        if padded_n != n:
            out = jax.tree_util.tree_map(lambda x: x[:n], out)
        return out

    @property
    def params(self):
        return self.pipelined.params


def prepare_pippy(
    model,
    layered=None,
    num_microbatches: Optional[int] = None,
    mesh=None,
    compute_dtype=None,
    batch_to_args: Optional[Callable] = None,
) -> PipelineInferencer:
    """Stage-shard a model for pipelined inference (reference prepare_pippy
    inference.py:126; the name is kept for drop-in familiarity).

    Args:
        model: a `Model` bundle (accelerate_tpu.modeling).
        layered: the model's `LayeredApply` stage decomposition; defaults to
            `model.module.layered_apply()` when the flax module provides one.
        num_microbatches: batch chunks in flight (reference `num_chunks`, defaults to
            the number of pipeline stages — one chunk per stage).
        mesh: defaults to the active AcceleratorState mesh (must have a "stage" axis >1
            to actually pipeline; with stage=1 this degrades to plain chunked forward).
    """
    from .parallel.pipeline import PipelinedModel

    if mesh is None:
        mesh = AcceleratorState().mesh
    if layered is None:
        module = getattr(model, "module", None)
        maker = getattr(module, "layered_apply", None)
        if maker is None:
            raise ValueError(
                "Pass layered= (a LayeredApply stage decomposition); this model's module "
                "does not provide one."
            )
        layered = maker()
    if num_microbatches is None:
        num_microbatches = max(2, mesh.shape.get("stage", 1))
    pipelined = PipelinedModel(
        model,
        layered,
        mesh,
        num_microbatches=num_microbatches,
        compute_dtype=compute_dtype,
        batch_to_args=batch_to_args,
        remat=False,  # inference: nothing to rematerialize for
    )
    return PipelineInferencer(pipelined, mesh, num_microbatches)

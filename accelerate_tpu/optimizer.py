"""Optimizer wrapper (L3): optax under an Accelerate-shaped interface.

TPU-native redesign of reference optimizer.py (214 LoC). The reference's core trick —
lazily all-reducing gradients exactly once per optimizer step on XLA
(optimizer.py:140-146) — disappears here: gradients of a sharded-batch loss w.r.t.
replicated/sharded params already carry the correct psum/reduce-scatter from GSPMD. What
remains, and is kept contract-identical:

  - `step()` is a no-op while `GradientState.sync_gradients` is False (accumulation);
  - `zero_grad()` clears the accumulated gradient buffer;
  - fp16 dynamic loss scaling with skipped-step detection (`optimizer.step_was_skipped`,
    reference optimizer.py:153-168) — bf16 (the TPU default) never needs it;
  - gradient clipping folded into the jitted update (reference clips pre-step,
    accelerator.py:2221).

All device math is jitted with donated buffers: accumulate-add donates the accumulator,
the fused update donates (params, opt_state, grads).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .state import AcceleratorState, GradientState
from .utils.dataclasses import GradScalerKwargs

logger = get_logger(__name__)


class GradScaler:
    """Dynamic loss scaling for fp16 (reference uses torch.cuda.amp.GradScaler,
    accelerator.py:455-479; this is the functional JAX equivalent)."""

    def __init__(self, kwargs: Optional[GradScalerKwargs] = None):
        kwargs = kwargs or GradScalerKwargs()
        self.scale = float(kwargs.init_scale)
        self.growth_factor = kwargs.growth_factor
        self.backoff_factor = kwargs.backoff_factor
        self.growth_interval = kwargs.growth_interval
        self.enabled = kwargs.enabled
        self._growth_tracker = 0

    def update(self, found_inf: bool):
        if not self.enabled:
            return
        if found_inf:
            self.scale *= self.backoff_factor
            self._growth_tracker = 0
        else:
            self._growth_tracker += 1
            if self._growth_tracker >= self.growth_interval:
                self.scale *= self.growth_factor
                self._growth_tracker = 0

    def state_dict(self):
        return {"scale": self.scale, "growth_tracker": self._growth_tracker}

    def load_state_dict(self, state):
        self.scale = state["scale"]
        self._growth_tracker = state["growth_tracker"]


def apply_update_core(
    tx,
    params,
    opt_state,
    grads,
    inv_scale,
    lr_override=None,
    *,
    use_scaler: bool = False,
    max_norm: Optional[float] = None,
):
    """Shared traced body of the optimizer update, used by both the eager
    `AcceleratedOptimizer._update_fn` and the fused train step so their semantics
    cannot drift: unscale grads -> finite check -> optional global-norm clip ->
    optional LR override -> tx.update -> skip-revert on non-finite.

    Matches the reference ordering: gradients are unscaled BEFORE clipping
    (reference accelerator.py:2186 unscale_gradients inside clip_grad_norm_).
    Returns (new_params, new_opt_state, finite).
    """
    import jax
    import jax.numpy as jnp

    grads = jax.tree_util.tree_map(lambda g: g * inv_scale, grads)
    finite = jnp.array(True)
    if use_scaler:
        finite = jnp.all(
            jnp.stack([jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)])
        )
    if max_norm is not None:
        norm = jnp.sqrt(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads))
        )
        factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        grads = jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads)
    if lr_override is not None and hasattr(opt_state, "hyperparams"):
        opt_state = opt_state._replace(hyperparams={**opt_state.hyperparams, "learning_rate": lr_override})
    updates, new_opt_state = tx.update(grads, opt_state, params)
    new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    if use_scaler:
        # Skipped step on non-finite grads: keep the old state untouched.
        new_params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(finite, new, old), new_params, params
        )
        new_opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(finite, new, old) if hasattr(new, "shape") else new,
            new_opt_state,
            opt_state,
        )
    return new_params, new_opt_state, finite


class AcceleratedOptimizer:
    """Wraps an `optax.GradientTransformation` bound to a `PreparedModel`
    (reference AcceleratedOptimizer optimizer.py:38).

    Holds the (sharded) optimizer state and the gradient-accumulation buffer; `step()`
    applies the fused, jitted update and writes new params back into the model.
    """

    def __init__(
        self,
        optimizer,
        model=None,
        scaler: Optional[GradScaler] = None,
        mesh=None,
        fsdp_plugin=None,
    ):
        import jax

        self.tx = optimizer
        self.model = model
        self.scaler = scaler
        self.gradient_state = GradientState()
        self.step_was_skipped = False
        self._accum_count = 0
        self._grads = None
        self._grads_unscaled = False  # set by clip_*: grads already divided by loss scale
        self._jit_cache: dict = {}

        self.offload_opt_state = False
        self._opt_compute_sharding = None
        if model is not None:
            from .parallel.sharding import (
                derive_opt_state_shardings,
                host_memory_available,
                with_memory_kind,
            )

            if mesh is None:
                mesh = model.mesh
            self.mesh = mesh
            rules = getattr(model, "sharding_rules", None)
            if mesh is not None:
                state_shapes = jax.eval_shape(self.tx.init, model.params)
                self.opt_state_sharding = derive_opt_state_shardings(state_shapes, mesh, fsdp_plugin, rules)
                want_offload = bool(getattr(fsdp_plugin, "offload_optimizer_state", False))
                if want_offload and not host_memory_available():
                    logger.warning(
                        "offload_optimizer_state requested but this backend exposes no "
                        "pinned_host memory space; optimizer state stays in device memory."
                    )
                    want_offload = False
                if want_offload:
                    # ZeRO-offload tier (reference accelerator.py:1563-1785,
                    # dataclasses.py:704-719): optimizer state lives in pinned host
                    # memory; the update streams it to HBM inside the jitted step and
                    # the new state is written back host-side.
                    self.offload_opt_state = True
                    self._opt_compute_sharding = self.opt_state_sharding
                    self.opt_state_sharding = with_memory_kind(self.opt_state_sharding, "pinned_host")
                    dev_state = jax.jit(self.tx.init, out_shardings=self._opt_compute_sharding)(model.params)
                    self.opt_state = jax.device_put(dev_state, self.opt_state_sharding)
                else:
                    self.opt_state = jax.jit(self.tx.init, out_shardings=self.opt_state_sharding)(model.params)
            else:
                self.opt_state_sharding = None
                self.opt_state = self.tx.init(model.params)
        else:
            self.mesh = None
            self.opt_state_sharding = None
            self.opt_state = None

        self._lr_override = None

    # ---- offload tier movement -------------------------------------------------------
    def opt_to_compute_memory(self, opt_state):
        """Traceable: stream host-offloaded optimizer state into device memory
        (identity when not offloaded)."""
        import jax

        if self.offload_opt_state and self._opt_compute_sharding is not None:
            return jax.device_put(opt_state, self._opt_compute_sharding)
        return opt_state

    def opt_to_storage_memory(self, opt_state):
        """Eager: place updated optimizer state back on its storage tier."""
        import jax

        if self.offload_opt_state and self.opt_state_sharding is not None:
            return jax.device_put(opt_state, self.opt_state_sharding)
        return opt_state

    # ---- gradient intake -------------------------------------------------------------
    def _accumulate_fn(self):
        import jax

        if "acc" not in self._jit_cache:

            def _add(acc, new):
                return jax.tree_util.tree_map(lambda a, b: a + b, acc, new)

            self._jit_cache["acc"] = jax.jit(_add, donate_argnums=(0,))
        return self._jit_cache["acc"]

    def accumulate_grads(self, grads):
        """Add a microbatch's gradients into the accumulation buffer."""
        if self._grads is None:
            self._grads = grads
            self._grads_unscaled = False
        else:
            self._grads = self._accumulate_fn()(self._grads, grads)
        self._accum_count += 1

    @property
    def grads(self):
        return self._grads

    # ---- clipping --------------------------------------------------------------------
    def _unscale_factor(self) -> float:
        """1/loss_scale the first time grads are touched pre-step; 1.0 after
        (the reference's unscale_gradients-once contract, accelerator.py:2186)."""
        if self.scaler is not None and self.scaler.enabled and not self._grads_unscaled:
            self._grads_unscaled = True
            return 1.0 / self.scaler.scale
        return 1.0

    def clip_grad_norm_(self, max_norm: float):
        """Unscale then clip accumulated grads by global norm; returns the pre-clip
        (unscaled) norm (reference accelerator.py:2221-2269, which unscales first)."""
        import jax
        import jax.numpy as jnp

        if self._grads is None:
            return None
        inv_scale = self._unscale_factor()
        key = ("clip", float(max_norm))
        if key not in self._jit_cache:

            def _clip(grads, inv):
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                norm = jnp.sqrt(
                    sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads))
                )
                factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
                return jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads), norm

            self._jit_cache[key] = jax.jit(_clip, donate_argnums=(0,))
        self._grads, norm = self._jit_cache[key](self._grads, jnp.asarray(inv_scale, jnp.float32))
        return norm

    def clip_grad_value_(self, clip_value: float):
        import jax
        import jax.numpy as jnp

        if self._grads is None:
            return
        inv_scale = self._unscale_factor()
        key = ("clipv", float(clip_value))
        if key not in self._jit_cache:

            def _clip(grads, inv):
                return jax.tree_util.tree_map(lambda g: (g * inv).clip(-clip_value, clip_value), grads)

            self._jit_cache[key] = jax.jit(_clip, donate_argnums=(0,))
        self._grads = self._jit_cache[key](self._grads, jnp.asarray(inv_scale, jnp.float32))

    # ---- the update ------------------------------------------------------------------
    def _update_fn(self):
        import jax

        if "update" not in self._jit_cache:
            use_scaler = self.scaler is not None and self.scaler.enabled
            to_compute = getattr(self.model, "to_compute_memory", lambda p: p)

            def _update(params, opt_state, grads, inv_scale, lr_override):
                # Host-offloaded tiers stream into device memory for the update;
                # the caller writes the results back to pinned host.
                opt_state = self.opt_to_compute_memory(opt_state)
                params = to_compute(params)
                return apply_update_core(
                    self.tx, params, opt_state, grads, inv_scale, lr_override, use_scaler=use_scaler
                )

            donate = (0, 1, 2)
            self._jit_cache["update"] = jax.jit(_update, donate_argnums=donate)
        return self._jit_cache["update"]

    def step(self):
        """Apply the update if at a sync boundary; no-op otherwise (reference
        optimizer.py:125-152)."""
        import jax
        import jax.numpy as jnp

        if not self.gradient_state.sync_gradients:
            self.step_was_skipped = True
            return
        if self._grads is None:
            self.step_was_skipped = True
            return
        inv_scale = self._unscale_factor()
        lr = self._lr_override
        new_params, new_opt_state, finite = self._update_fn()(
            self.model.params, self.opt_state, self._grads, jnp.asarray(inv_scale, jnp.float32), lr
        )
        self._grads = None
        self._accum_count = 0
        self._grads_unscaled = False
        if self.scaler is not None and self.scaler.enabled:
            found_inf = not bool(finite)
            self.scaler.update(found_inf)
            self.step_was_skipped = found_inf
            if found_inf:
                logger.warning("Skipping optimizer step: non-finite gradients (loss scale -> %s)", self.scaler.scale)
        else:
            self.step_was_skipped = False
        if hasattr(self.model, "to_storage_memory"):
            new_params = self.model.to_storage_memory(new_params)
        self.model.params = new_params
        self.opt_state = self.opt_to_storage_memory(new_opt_state)

    def zero_grad(self, set_to_none: bool = True):
        """Clear accumulated grads; no-op mid-accumulation (reference optimizer.py:112)."""
        if self.gradient_state.sync_gradients:
            self._grads = None
            self._accum_count = 0
            self._grads_unscaled = False

    # ---- scheduler hook --------------------------------------------------------------
    def set_learning_rate(self, lr: float):
        """Override the learning rate for subsequent steps (requires the tx to be built
        with `optax.inject_hyperparams`, else schedules inside the tx govern)."""
        self._lr_override = lr

    @property
    def learning_rate(self):
        if self._lr_override is not None:
            return self._lr_override
        if hasattr(self.opt_state, "hyperparams"):
            lr = self.opt_state.hyperparams.get("learning_rate")
            return None if lr is None else float(np.asarray(lr))
        return None

    # ---- checkpoint view -------------------------------------------------------------
    def state_dict(self):
        return {"opt_state": self.opt_state, "scaler": self.scaler.state_dict() if self.scaler else None}

    def load_state_dict(self, state):
        from .parallel.sharding import place_params

        # place_params (not device_put): device_put aliases buffers already placed
        # correctly, and the donated update would delete the caller's arrays through
        # that alias on the next step.
        self.opt_state = place_params(state["opt_state"], self.opt_state_sharding)
        if self.scaler is not None and state.get("scaler") is not None:
            self.scaler.load_state_dict(state["scaler"])

"""Optimizer wrapper (L3): optax under an Accelerate-shaped interface.

TPU-native redesign of reference optimizer.py (214 LoC). The reference's core trick —
lazily all-reducing gradients exactly once per optimizer step on XLA
(optimizer.py:140-146) — disappears here: gradients of a sharded-batch loss w.r.t.
replicated/sharded params already carry the correct psum/reduce-scatter from GSPMD. What
remains, and is kept contract-identical:

  - `step()` is a no-op while `GradientState.sync_gradients` is False (accumulation);
  - `zero_grad()` clears the accumulated gradient buffer;
  - fp16 dynamic loss scaling with skipped-step detection (`optimizer.step_was_skipped`,
    reference optimizer.py:153-168) — bf16 (the TPU default) never needs it;
  - gradient clipping folded into the jitted update (reference clips pre-step,
    accelerator.py:2221).

All device math is jitted with donated buffers: accumulate-add donates the accumulator,
the fused update donates (params, opt_state, grads).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .state import AcceleratorState, GradientState
from .utils.dataclasses import GradScalerKwargs
from .utils.environment import fence_if_cpu

logger = get_logger(__name__)


class GradScaler:
    """Dynamic loss scaling for fp16 (reference uses torch.cuda.amp.GradScaler,
    accelerator.py:455-479; this is the functional JAX equivalent)."""

    def __init__(self, kwargs: Optional[GradScalerKwargs] = None):
        kwargs = kwargs or GradScalerKwargs()
        self.scale = float(kwargs.init_scale)
        self.growth_factor = kwargs.growth_factor
        self.backoff_factor = kwargs.backoff_factor
        self.growth_interval = kwargs.growth_interval
        self.enabled = kwargs.enabled
        self._growth_tracker = 0

    def update(self, found_inf: bool):
        if not self.enabled:
            return
        if found_inf:
            self.scale *= self.backoff_factor
            self._growth_tracker = 0
        else:
            self._growth_tracker += 1
            if self._growth_tracker >= self.growth_interval:
                self.scale *= self.growth_factor
                self._growth_tracker = 0

    def state_dict(self):
        return {"scale": self.scale, "growth_tracker": self._growth_tracker}

    def load_state_dict(self, state):
        self.scale = state["scale"]
        self._growth_tracker = state["growth_tracker"]


def unscale_and_clip(grads, inv_scale, max_norm: Optional[float], use_scaler: bool):
    """Traced: unscale -> finite check -> optional global-norm clip. The ONE place
    this logic lives; apply_update_core and the offload grads program share it.
    Returns (grads, finite)."""
    import jax
    import jax.numpy as jnp

    # Preserve the gradient dtype: inv_scale is a strong fp32 scalar and would
    # silently promote bf16 grads (and through them the whole update + params)
    # to fp32, breaking param_dtype storage.
    grads = jax.tree_util.tree_map(lambda g: (g * inv_scale).astype(g.dtype), grads)
    finite = jnp.array(True)
    if use_scaler:
        finite = jnp.all(
            jnp.stack([jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)])
        )
    if max_norm is not None:
        norm = jnp.sqrt(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads))
        )
        factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        grads = jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads)
    return grads, finite


def update_and_revert(tx, params, opt_state, grads, lr_override, finite, use_scaler: bool):
    """Traced: optional LR override -> tx.update -> skip-revert on non-finite. Shared
    by the whole-tree update and each chunked-offload group program.
    Returns (new_params, new_opt_state)."""
    import jax
    import jax.numpy as jnp

    if lr_override is not None and hasattr(opt_state, "hyperparams"):
        opt_state = opt_state._replace(hyperparams={**opt_state.hyperparams, "learning_rate": lr_override})
    updates, new_opt_state = tx.update(grads, opt_state, params)
    new_params = jax.tree_util.tree_map(lambda p, u: (p + u).astype(p.dtype), params, updates)
    if use_scaler:
        # Skipped step on non-finite grads: keep the old state untouched.
        new_params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(finite, new, old), new_params, params
        )
        new_opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(finite, new, old) if hasattr(new, "shape") else new,
            new_opt_state,
            opt_state,
        )
    return new_params, new_opt_state


def apply_update_core(
    tx,
    params,
    opt_state,
    grads,
    inv_scale,
    lr_override=None,
    *,
    use_scaler: bool = False,
    max_norm: Optional[float] = None,
):
    """Shared traced body of the optimizer update, used by both the eager
    `AcceleratedOptimizer._update_fn` and the fused train step so their semantics
    cannot drift: unscale grads -> finite check -> optional global-norm clip ->
    optional LR override -> tx.update -> skip-revert on non-finite.

    Matches the reference ordering: gradients are unscaled BEFORE clipping
    (reference accelerator.py:2186 unscale_gradients inside clip_grad_norm_).
    Returns (new_params, new_opt_state, finite).
    """
    grads, finite = unscale_and_clip(grads, inv_scale, max_norm, use_scaler)
    new_params, new_opt_state = update_and_revert(
        tx, params, opt_state, grads, lr_override, finite, use_scaler
    )
    return new_params, new_opt_state, finite


class DiskOptState:
    """Optimizer state resident on DISK — the NVMe tier of ZeRO-offload
    (reference DeepSpeed fields dataclasses.py:704-719).

    Param-shaped slots (Adam moments, ...) live in one NativeOffloadStore blob
    keyed "slot{i}/{param_path}"; shared scalar slots (step counts,
    hyperparams) stay in memory. The chunked update loop async-prefetches group
    N+1 while group N's program runs and writes results back in place, so peak
    HBM *and* host RSS stay at one parameter group."""

    def __init__(self, store, state_def, slot_is_param, scalars, param_paths, decompose, recompose):
        self.store = store
        self.state_def = state_def
        self.slot_is_param = slot_is_param
        self.scalars = scalars
        self.param_paths = param_paths
        self._decompose = decompose
        self._recompose = recompose
        # A step that failed after some groups' write-backs leaves the blob
        # partially advanced relative to the params (the in-memory tier only
        # commits after the whole loop). Poison the state so a retry fails loudly
        # instead of silently double-applying moment updates; load() clears it.
        self.poisoned = False

    def check_usable(self):
        if self.poisoned:
            raise RuntimeError(
                "disk optimizer state is inconsistent: a previous step failed after "
                "some parameter groups were written back. Restore with load_state() "
                "(or rebuild the optimizer) before continuing."
            )

    def prefetch_group(self, paths):
        self.store.prefetch_many(
            [f"slot{i}/{p}" for i, is_p in enumerate(self.slot_is_param) if is_p for p in paths]
        )

    def read_group(self, paths, scalars=None):
        """Group state pytree; `scalars` overrides the in-memory slot values (the
        chunked loop passes a pre-step snapshot so every group sees the ORIGINAL
        shared scalars, not a prior group's increment)."""
        scalars = self.scalars if scalars is None else scalars
        vals = []
        for i, is_p in enumerate(self.slot_is_param):
            if is_p:
                vals.append({p: self.store.read(f"slot{i}/{p}") for p in paths})
            else:
                vals.append(scalars[i])
        return self.state_def.unflatten(vals)

    def write_group(self, paths, new_group_state):
        import jax

        for i, val in enumerate(self.state_def.flatten_up_to(new_group_state)):
            if self.slot_is_param[i]:
                for p in paths:
                    self.store.write(f"slot{i}/{p}", np.asarray(jax.device_get(val[p])))
            else:
                self.scalars[i] = val

    def materialize(self):
        """Full state pytree on host (checkpointing; costs one pass over the blob)."""
        slots = [
            {p: self.store.read(f"slot{i}/{p}") for p in self.param_paths} if is_p else self.scalars[i]
            for i, is_p in enumerate(self.slot_is_param)
        ]
        return self._recompose(slots, self.state_def)

    def load(self, full_state):
        """Overwrite the blob from a full state pytree (checkpoint restore)."""
        import jax

        slots, _ = self._decompose(full_state)
        for i, slot in enumerate(slots):
            if self.slot_is_param[i]:
                for p, arr in slot.items():
                    self.store.write(f"slot{i}/{p}", np.asarray(jax.device_get(arr)))
            else:
                self.scalars[i] = slot
        self.poisoned = False


class AcceleratedOptimizer:
    """Wraps an `optax.GradientTransformation` bound to a `PreparedModel`
    (reference AcceleratedOptimizer optimizer.py:38).

    Holds the (sharded) optimizer state and the gradient-accumulation buffer; `step()`
    applies the fused, jitted update and writes new params back into the model.
    """

    def __init__(
        self,
        optimizer,
        model=None,
        scaler: Optional[GradScaler] = None,
        mesh=None,
        fsdp_plugin=None,
    ):
        import jax

        self.tx = optimizer
        self.model = model
        self.scaler = scaler
        self.gradient_state = GradientState()
        self.step_was_skipped = False
        self._accum_count = 0
        self._grads = None
        self._grads_unscaled = False  # set by clip_*: grads already divided by loss scale
        self._jit_cache: dict = {}

        self.offload_opt_state = False
        self._opt_compute_sharding = None
        self.is_mpmd = model is not None and getattr(model, "is_mpmd", False)
        if self.is_mpmd:
            # MPMD pipeline model: optimizer state lives PER STAGE, each piece
            # on its own stage submesh placed by that stage's ZeRO opt-rules
            # table — a single-mesh opt_state/opt_state_sharding here would be
            # meaningless (model.params spans several disjoint meshes). The
            # model owns the per-stage states and the per-stage update
            # programs; the step itself runs through Accelerator.train_step.
            self.mesh = mesh if mesh is not None else getattr(model, "mesh", None)
            self.opt_state_sharding = None
            self.opt_state = None
            model.init_optimizer_state(self.tx)
            self._lr_override = None
            return
        if model is not None:
            from .parallel.sharding import (
                derive_opt_state_shardings,
                host_memory_available,
                host_memory_kind,
                with_memory_kind,
            )

            if mesh is None:
                mesh = model.mesh
            self.mesh = mesh
            rules = getattr(model, "sharding_rules", None)
            # Planner-emitted ZeRO table (plan.opt_rules, stamped on the bundle
            # by prepare_model under sharding_rules="auto"): authoritative for
            # matched moments — shards the weight update along "data" even
            # where the params replicate.
            opt_rules = getattr(model, "opt_sharding_rules", None)
            if mesh is not None:
                state_shapes = jax.eval_shape(self.tx.init, model.params)
                self.opt_state_sharding = derive_opt_state_shardings(
                    state_shapes, mesh, fsdp_plugin, rules, opt_rules=opt_rules
                )
                offload_device = str(getattr(fsdp_plugin, "offload_optimizer_device", None) or "").lower()
                want_disk = offload_device in ("disk", "nvme")
                want_offload = bool(getattr(fsdp_plugin, "offload_optimizer_state", False)) and not want_disk
                if want_offload and not host_memory_available():
                    logger.warning(
                        "offload_optimizer_state requested but this backend exposes no "
                        "host-tier memory space (pinned_host/unpinned_host); optimizer "
                        "state stays in device memory."
                    )
                    want_offload = False
                if want_disk:
                    # NVMe tier: needs no pinned_host memory space — staging runs
                    # through host numpy around each group program.
                    import tempfile

                    directory = getattr(fsdp_plugin, "offload_dir", None) or tempfile.mkdtemp(
                        prefix="accelerate_tpu_optstate_"
                    )
                    self.offload_opt_state = True
                    self._opt_compute_sharding = self.opt_state_sharding
                    self.opt_state = self._disk_offload_init(model.params, state_shapes, directory)
                elif want_offload:
                    # ZeRO-offload tier (reference accelerator.py:1563-1785,
                    # dataclasses.py:704-719): optimizer state lives in pinned host
                    # memory; updates stream it through HBM one param GROUP at a
                    # time (apply_chunked_update). Init is chunked the same way —
                    # materializing the full state on device first would OOM by
                    # itself (fp32 Adam moments are 8 bytes/param: 12 GB for
                    # llama-1b against a 16 GB chip).
                    self.offload_opt_state = True
                    self._opt_compute_sharding = self.opt_state_sharding
                    self.opt_state_sharding = with_memory_kind(
                        self.opt_state_sharding, host_memory_kind()
                    )
                    self.opt_state = self._chunked_offload_init(model.params, state_shapes)
                else:
                    self.opt_state = jax.jit(self.tx.init, out_shardings=self.opt_state_sharding)(model.params)
            else:
                self.opt_state_sharding = None
                self.opt_state = self.tx.init(model.params)
        else:
            self.mesh = None
            self.opt_state_sharding = None
            self.opt_state = None

        self._lr_override = None

    # ---- MPMD guard ------------------------------------------------------------------
    def _reject_mpmd(self, what: str) -> None:
        """Fail loudly, not deep inside the update machinery: on the MPMD
        pipeline route this wrapper holds NO single-mesh opt_state (it lives
        per stage, on per-stage submeshes, owned by the model) — mirrors the
        error Accelerator.backward() raises on the same route."""
        if getattr(self, "is_mpmd", False):
            raise NotImplementedError(
                f"{what} operates on a single-mesh optimizer state, but this "
                "optimizer is bound to an MPMD pipeline model whose optimizer "
                "state lives per stage on per-stage submeshes. Use step_fn = "
                "Accelerator.train_step() — it runs the 1F1B schedule with "
                "per-stage accumulation and updates."
            )

    # ---- offload tier movement -------------------------------------------------------
    def opt_to_compute_memory(self, opt_state):
        """Traceable: stream host-offloaded optimizer state into device memory
        (identity when not offloaded)."""
        import jax

        if self.offload_opt_state and self._opt_compute_sharding is not None:
            return jax.device_put(opt_state, self._opt_compute_sharding)
        return opt_state

    def opt_to_storage_memory(self, opt_state):
        """Eager: place updated optimizer state back on its storage tier."""
        import jax

        if self.offload_opt_state and self.opt_state_sharding is not None:
            return jax.device_put(opt_state, self.opt_state_sharding)
        return opt_state

    # ---- chunked offload update ------------------------------------------------------
    # True ZeRO-offload cannot stream the WHOLE optimizer state to HBM for the
    # update: for llama-1b the fp32 Adam moments alone are 12 GB against a 16 GB
    # v5e chip (measured OOM). Instead the update runs as one small program per
    # parameter GROUP, so peak device memory is one group's params+grads+state.
    # The reference reaches the same place with DeepSpeed's CPU-Adam
    # (accelerator.py:1563-1785); here each group program is still an XLA program
    # with the streaming H2D/D2H on the program boundary.

    # ---- disk (NVMe) tier ------------------------------------------------------------
    def _disk_offload_init(self, params, state_shapes, directory):
        """Build the DISK-resident optimizer state (DeepSpeed NVMe-offload parity,
        reference dataclasses.py:704-719): per-group tx.init on device -> host ->
        one NativeOffloadStore blob; shared scalars (step counts, hyperparams)
        stay in memory. Neither HBM nor host RSS ever holds more than one group."""
        import jax

        from .native.offload import NativeOffloadStore
        from .parallel.sharding import tree_paths_and_leaves

        logger.warning_once(
            "offload_optimizer_device=disk: optimizer state lives in %s and updates "
            "run per parameter group (chunked streaming with async prefetch). "
            "Optax transforms needing cross-parameter statistics would compute them "
            "per group; use max_grad_norm / clip_grad_norm_ for global clipping.",
            directory,
        )
        groups = self._offload_groups(params)
        self._jit_cache["chunk_groups"] = groups
        self._jit_cache["chunk_slicer"] = self._state_slicer(params)
        chunker = self._state_chunker(params)
        self._jit_cache["chunk_chunker"] = chunker
        decompose, _group_state, _absorb, recompose = chunker
        slots_shapes, state_def = decompose(state_shapes)
        slot_is_param = [isinstance(s, dict) for s in slots_shapes]
        flat_params = dict(tree_paths_and_leaves(params)[0])

        store = NativeOffloadStore(directory)
        # Fresh state, fresh blob: a leftover store from a previous run holds
        # stale entries whose bytes would be orphaned by the append-then-repoint
        # save(), growing the blob by a full state copy per restart.
        store.reset()
        scalars = [None] * len(slots_shapes)
        for paths in groups:
            p_g = {p: flat_params[p] for p in paths}
            s_g = jax.jit(self.tx.init)(p_g)  # tpu-lint: disable=jit-in-loop (one-shot setup per group)
            for i, val in enumerate(state_def.flatten_up_to(s_g)):
                if slot_is_param[i]:
                    store.save(
                        {f"slot{i}/{p}": np.asarray(jax.device_get(a)) for p, a in val.items()},
                        flush_index=False,
                    )
                else:
                    scalars[i] = val
            del s_g  # one group of device state at a time
        store.flush_index()
        all_paths = [p for g in groups for p in g]
        return DiskOptState(store, state_def, slot_is_param, scalars, all_paths, decompose, recompose)

    def _offload_groups(self, params):
        """Partition param leaf-paths into groups under a byte budget."""
        import os

        import numpy as np

        from .parallel.sharding import tree_paths_and_leaves

        budget = int(os.environ.get("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "256")) * 1024 * 1024
        groups, cur, cur_bytes = [], [], 0
        for path, leaf in tree_paths_and_leaves(params)[0]:
            nbytes = int(np.prod(np.shape(leaf))) * getattr(leaf, "dtype", np.dtype("float32")).itemsize
            if cur and cur_bytes + nbytes > budget:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(path)
            cur_bytes += nbytes
        if cur:
            groups.append(cur)
        return groups

    def _chunked_offload_init(self, params, state_shapes):
        """Build the pinned-host optimizer state without ever holding more than one
        group's state in HBM: per-group tx.init on device -> pinned-host writeback,
        then assemble the global tree directly from the group pieces (no full-size
        zeros skeleton). Group-independent scalars (step counts, hyperparams) take
        the last group's init value — identical across groups for any element-wise
        transform; transforms needing cross-parameter state are unsupported here
        (warned below) — use max_grad_norm/clip_grad_norm_ for global clipping."""
        import jax

        from .parallel.sharding import tree_paths_and_leaves

        logger.warning_once(
            "offload_optimizer_state: updates run per parameter group (chunked "
            "streaming). Optax transforms needing cross-parameter statistics inside "
            "the chain (e.g. clip_by_global_norm) would compute them per group; use "
            "max_grad_norm / clip_grad_norm_ for global clipping instead."
        )
        groups = self._offload_groups(params)
        slice_state = self._state_slicer(params)
        self._jit_cache["chunk_groups"] = groups
        self._jit_cache["chunk_slicer"] = slice_state
        ptreedef, param_paths, is_param_shaped, _to_flat = self._param_tree_tools(params)
        flat_params = dict(tree_paths_and_leaves(params)[0])

        group_states = []
        for paths in groups:
            p_g = {p: flat_params[p] for p in paths}
            s_g = jax.jit(self.tx.init)(p_g)  # tpu-lint: disable=jit-in-loop (one-shot setup per group)
            group_states.append(jax.device_put(s_g, slice_state(self.opt_state_sharding, paths)))

        def assemble(template_node, *group_nodes):
            if is_param_shaped(template_node):
                flat = {}
                for gn in group_nodes:
                    flat.update(gn)
                return jax.tree_util.tree_unflatten(ptreedef, [flat[p] for p in param_paths])
            return group_nodes[-1]

        return jax.tree_util.tree_map(assemble, state_shapes, *group_states, is_leaf=is_param_shaped)

    @staticmethod
    def _param_tree_tools(params):
        """Shared decomposition contract for optax states whose subtrees mirror the
        params treedef (adam/sgd/adafactor-family — every element-wise transform):
        (ptreedef, param_paths, is_param_shaped, to_flat)."""
        import jax

        from .parallel.sharding import tree_paths_and_leaves

        ptreedef = jax.tree_util.tree_structure(params)
        param_paths = [p for p, _ in tree_paths_and_leaves(params)[0]]

        def is_param_shaped(x):
            try:
                return jax.tree_util.tree_structure(x) == ptreedef
            except Exception:
                return False

        def to_flat(subtree):
            return dict(zip(param_paths, jax.tree_util.tree_leaves(subtree)))

        return ptreedef, param_paths, is_param_shaped, to_flat

    def _state_slicer(self, params):
        """slice_fn(state, paths) -> group state with param-mirroring subtrees
        replaced by flat {path: leaf} dicts (used for states AND their sharding
        trees; the write-back side lives in _state_chunker)."""
        import jax

        _ptreedef, _param_paths, is_param_shaped, to_flat = self._param_tree_tools(params)

        def slice_state(state, paths):
            pathset = set(paths)
            return jax.tree_util.tree_map(
                # Param-shaped subtrees (mu/nu/...) slice to the group's leaves;
                # anything else (step counts, hyperparams scalars) passes through.
                lambda sub: {p: v for p, v in to_flat(sub).items() if p in pathset}
                if is_param_shaped(sub)
                else sub,
                state,
                is_leaf=is_param_shaped,
            )

        return slice_state

    def _state_chunker(self, params):
        """O(P)-per-step decomposition of an optax state for the chunked-offload loop
        (vs O(groups x P) for slice-per-group): `decompose` flattens the state
        ONCE into slots (param-shaped subtrees -> path-keyed dicts, scalars as-is),
        `group_state` builds a group's sliced state in O(|group|), `absorb` writes a
        group's updated slots back in O(|group|), `recompose` rebuilds the full tree
        once after the loop."""
        import jax

        ptreedef, param_paths, is_param_shaped, to_flat = self._param_tree_tools(params)

        def decompose(state):
            leaves, state_def = jax.tree_util.tree_flatten(state, is_leaf=is_param_shaped)
            slots = [to_flat(l) if is_param_shaped(l) else l for l in leaves]
            return slots, state_def

        def group_state(slots, state_def, paths):
            return state_def.unflatten(
                [{p: d[p] for p in paths} if isinstance(d, dict) else d for d in slots]
            )

        def absorb(slots, state_def, new_group_state):
            # flatten_up_to stops at state_def's leaf positions, so each value is the
            # group's path-dict (param slot) or scalar (shared slot; last group wins).
            for i, val in enumerate(state_def.flatten_up_to(new_group_state)):
                if isinstance(slots[i], dict):
                    slots[i].update(val)
                else:
                    slots[i] = val

        def recompose(slots, state_def):
            return state_def.unflatten(
                [
                    jax.tree_util.tree_unflatten(ptreedef, [d[p] for p in param_paths])
                    if isinstance(d, dict)
                    else d
                    for d in slots
                ]
            )

        return decompose, group_state, absorb, recompose

    def apply_chunked_update(self, params, grads, inv_scale, lr_override, finite=None):
        """Offload-tier update: global finite check first (an fp16 skipped step must
        leave every group untouched), then tx.update one group at a time with the
        group's state streamed pinned_host -> HBM -> pinned_host around its program.
        `finite` may be precomputed by the caller's grads program.
        Returns (new_params, finite).

        NOTE: tx.update runs per GROUP, which is exact for element-wise transforms
        (adam/sgd/adafactor families). A transform needing cross-parameter statistics
        inside the chain (e.g. optax.clip_by_global_norm) would compute them per
        group — use `max_grad_norm` / `clip_grad_norm_` instead (warned at init)."""
        import jax
        import jax.numpy as jnp

        use_scaler = self.scaler is not None and self.scaler.enabled
        with_lr = lr_override is not None

        params_offloaded = bool(getattr(self.model, "offload_params", False))
        if "chunk_groups" not in self._jit_cache:
            self._jit_cache["chunk_groups"] = self._offload_groups(params)
            self._jit_cache["chunk_slicer"] = self._state_slicer(params)
        if "chunk_chunker" not in self._jit_cache:
            self._jit_cache["chunk_chunker"] = self._state_chunker(params)
        if "chunk_static" not in self._jit_cache:
            # Static tree metadata: paths, treedef, and the offload-tier sharding
            # flat-dicts never change after init; per-step values are re-zipped
            # against the cached paths below (tree_leaves order is deterministic).
            ptreedef, param_paths, _ips, _tf = self._param_tree_tools(params)
            from .parallel.sharding import tree_paths_and_leaves

            p_compute_flat = p_storage_flat = None
            if params_offloaded:
                p_compute_flat = dict(tree_paths_and_leaves(self.model.param_compute_sharding)[0])
                p_storage_flat = dict(tree_paths_and_leaves(self.model.param_sharding)[0])
            self._jit_cache["chunk_static"] = (ptreedef, param_paths, p_compute_flat, p_storage_flat)
        groups = self._jit_cache["chunk_groups"]
        slice_state = self._jit_cache["chunk_slicer"]
        decompose, group_state, absorb, recompose = self._jit_cache["chunk_chunker"]
        params_treedef, param_paths, p_compute_flat, p_storage_flat = self._jit_cache["chunk_static"]
        flat_params = dict(zip(param_paths, jax.tree_util.tree_leaves(params)))
        flat_grads = dict(zip(param_paths, jax.tree_util.tree_leaves(grads)))

        if finite is None:
            finite = jnp.array(True)
            if use_scaler:
                if "chunk_finite" not in self._jit_cache:
                    self._jit_cache["chunk_finite"] = jax.jit(
                        lambda g, inv: unscale_and_clip(g, inv, None, True)[1]
                    )
                finite = self._jit_cache["chunk_finite"](grads, jnp.asarray(float(inv_scale), jnp.float32))

        new_flat = dict(flat_params)
        disk_state = self.opt_state if isinstance(self.opt_state, DiskOptState) else None
        if disk_state is None:
            state_slots, state_def = decompose(self.opt_state)
            # Reads come from state_slots (every group's update must see the ORIGINAL
            # shared scalars — e.g. Adam's count — not a prior group's increment);
            # writes land in out_slots. Param-slot dicts are shared objects, which is
            # safe: groups touch disjoint path sets.
            out_slots = list(state_slots)
        else:
            disk_state.check_usable()
            # Same original-scalars contract for the disk tier: snapshot the
            # in-memory scalar slots before any group writes its increment back.
            scalar_snapshot = list(disk_state.scalars)
            disk_state.prefetch_group(groups[0])
            if "disk_writer" not in self._jit_cache:
                import concurrent.futures

                self._jit_cache["disk_writer"] = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="optstate-writeback"
                )
            writer = self._jit_cache["disk_writer"]
            write_futures = []
        # Scalars change rarely: cache their device buffers (same rationale as the
        # fused step's _scalar_bufs — no per-step H2D for constants).
        skey = (float(inv_scale), float(lr_override) if with_lr else 0.0)
        if skey != self._jit_cache.get("chunk_scalar_key"):
            self._jit_cache["chunk_scalar_key"] = skey
            self._jit_cache["chunk_scalar_bufs"] = tuple(jnp.asarray(v, jnp.float32) for v in skey)
        inv_buf, lr_val = self._jit_cache["chunk_scalar_bufs"]
        try:
            new_params, finite = self._chunked_group_loop(
                groups,
                slice_state,
                group_state,
                absorb,
                recompose,
                disk_state=disk_state,
                flat_params=flat_params,
                flat_grads=flat_grads,
                params_offloaded=params_offloaded,
                p_compute_flat=p_compute_flat,
                p_storage_flat=p_storage_flat,
                inv_buf=inv_buf,
                lr_val=lr_val,
                finite=finite,
                with_lr=with_lr,
                use_scaler=use_scaler,
                new_flat=new_flat,
                state_slots=None if disk_state is not None else state_slots,
                state_def=None if disk_state is not None else state_def,
                out_slots=None if disk_state is not None else out_slots,
                scalar_snapshot=None if disk_state is None else scalar_snapshot,
                writer=None if disk_state is None else writer,
                write_futures=None if disk_state is None else write_futures,
                params_treedef=params_treedef,
                param_paths=param_paths,
            )
        except BaseException:
            # Group programs donate the grad buffers, so whatever accumulation
            # produced them is dead — drop it so the next backward starts fresh.
            self._grads = None
            self._accum_count = 0
            self._grads_unscaled = False
            if disk_state is not None:
                # Some groups' moment write-backs may already have landed while
                # the params were never assigned — the blob is now ahead of the
                # params. Poison so a blind retry fails loudly (load_state clears).
                for fut in write_futures:
                    try:
                        fut.result()
                    except Exception:
                        pass
                disk_state.poisoned = True
            raise
        return new_params, finite

    def _chunked_group_loop(
        self,
        groups,
        slice_state,
        group_state,
        absorb,
        recompose,
        *,
        disk_state,
        flat_params,
        flat_grads,
        params_offloaded,
        p_compute_flat,
        p_storage_flat,
        inv_buf,
        lr_val,
        finite,
        with_lr,
        use_scaler,
        new_flat,
        state_slots,
        state_def,
        out_slots,
        scalar_snapshot,
        writer,
        write_futures,
        params_treedef,
        param_paths,
    ):
        import jax
        import jax.numpy as jnp

        if disk_state is not None:
            state_def = disk_state.state_def
        for gi, paths in enumerate(groups):
            key = ("chunk_update", gi, with_lr)
            if key not in self._jit_cache:
                compute_shardings = slice_state(self._opt_compute_sharding, paths)
                p_compute = {p: p_compute_flat[p] for p in paths} if params_offloaded else None
                tx = self.tx

                def _group_update(p_g, s_g, g_g, inv, lr, finite, _sh=compute_shardings, _psh=p_compute):
                    s_g = jax.device_put(s_g, _sh)
                    if _psh is not None:
                        p_g = jax.device_put(p_g, _psh)
                    # Match the param dtype (same two hazards as _update_fn /
                    # unscale_and_clip): the fp32 `inv` scalar would promote bf16
                    # grads, and a reduce_dtype fp32 accumulation buffer must not
                    # leak fp32 moments into the (offload-halved) opt state.
                    g_g = jax.tree_util.tree_map(
                        lambda g, p: (g * inv).astype(p.dtype), g_g, p_g
                    )
                    return update_and_revert(
                        tx, p_g, s_g, g_g, lr if with_lr else None, finite, use_scaler
                    )

                # Disk tier: keep the caller's param buffers alive through the
                # step — a failed blob write-back must leave params usable for
                # the poison -> load_state recovery path (only grads donate).
                donate = (2,) if disk_state is not None else (0, 2)
                # tpu-lint: disable=jit-in-loop (memoized in _jit_cache per group key)
                self._jit_cache[key] = jax.jit(_group_update, donate_argnums=donate)
                self._jit_cache[("chunk_store_shard", gi)] = slice_state(self.opt_state_sharding, paths)
                self._jit_cache[("chunk_param_store", gi)] = (
                    {p: p_storage_flat[p] for p in paths} if params_offloaded else None
                )
            p_g = {p: flat_params[p] for p in paths}
            g_g = {p: flat_grads[p] for p in paths}
            if disk_state is not None:
                # Disk tier: async-prefetch the NEXT group's blob reads, consume
                # this group's (pre-step scalars from the snapshot), and hand the
                # write-back to the background thread so D2H + pwrite overlap the
                # next group's program.
                if gi + 1 < len(groups):
                    disk_state.prefetch_group(groups[gi + 1])
                s_g = disk_state.read_group(paths, scalars=scalar_snapshot)
                p_new, s_new = self._jit_cache[key](p_g, s_g, g_g, inv_buf, lr_val, finite)
                write_futures.append(writer.submit(disk_state.write_group, paths, s_new))
            else:
                s_g = group_state(state_slots, state_def, paths)
                p_new, s_new = self._jit_cache[key](p_g, s_g, g_g, inv_buf, lr_val, finite)
                # Write the group state straight back to its pinned-host tier (the
                # D2H overlaps the next group program) and absorb into the slots.
                s_new = jax.device_put(s_new, self._jit_cache[("chunk_store_shard", gi)])
                absorb(out_slots, state_def, s_new)
            if params_offloaded:
                p_new = jax.device_put(p_new, self._jit_cache[("chunk_param_store", gi)])
            new_flat.update(p_new)

        if disk_state is not None:
            for fut in write_futures:
                fut.result()  # surface write errors; state stays disk-resident
        else:
            self.opt_state = recompose(out_slots, state_def)
        new_params = jax.tree_util.tree_unflatten(params_treedef, [new_flat[p] for p in param_paths])
        return new_params, finite

    # ---- gradient intake -------------------------------------------------------------
    def _accumulate_fn(self):
        import jax

        if "acc" not in self._jit_cache:

            def _add(acc, new):
                return jax.tree_util.tree_map(lambda a, b: a + b.astype(a.dtype), acc, new)

            self._jit_cache["acc"] = jax.jit(_add, donate_argnums=(0,))
        return self._jit_cache["acc"]

    def accumulate_grads(self, grads):
        """Add a microbatch's gradients into the accumulation buffer (held in the
        model's reduce_dtype when set — FSDP MixedPrecision parity; cast back to
        the param dtype at step time by _update's grads.astype)."""
        self._reject_mpmd("accumulate_grads()")
        if self._grads is None:
            reduce_dtype = getattr(self.model, "reduce_dtype", None)
            if reduce_dtype is not None:
                import jax

                grads = jax.tree_util.tree_map(lambda g: g.astype(reduce_dtype), grads)
            self._grads = grads
            self._grads_unscaled = False
        else:
            self._grads = self._accumulate_fn()(self._grads, grads)
        self._accum_count += 1

    @property
    def grads(self):
        return self._grads

    # ---- clipping --------------------------------------------------------------------
    def _unscale_factor(self) -> float:
        """1/loss_scale the first time grads are touched pre-step; 1.0 after
        (the reference's unscale_gradients-once contract, accelerator.py:2186)."""
        if self.scaler is not None and self.scaler.enabled and not self._grads_unscaled:
            self._grads_unscaled = True
            return 1.0 / self.scaler.scale
        return 1.0

    def clip_grad_norm_(self, max_norm: float):
        """Unscale then clip accumulated grads by global norm; returns the pre-clip
        (unscaled) norm (reference accelerator.py:2221-2269, which unscales first)."""
        import jax
        import jax.numpy as jnp

        self._reject_mpmd("clip_grad_norm_()")
        if self._grads is None:
            return None
        inv_scale = self._unscale_factor()
        key = ("clip", float(max_norm))
        if key not in self._jit_cache:

            def _clip(grads, inv):
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                norm = jnp.sqrt(
                    sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads))
                )
                factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
                return jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads), norm

            self._jit_cache[key] = jax.jit(_clip, donate_argnums=(0,))
        self._grads, norm = self._jit_cache[key](self._grads, jnp.asarray(inv_scale, jnp.float32))
        return norm

    def clip_grad_value_(self, clip_value: float):
        import jax
        import jax.numpy as jnp

        self._reject_mpmd("clip_grad_value_()")
        if self._grads is None:
            return
        inv_scale = self._unscale_factor()
        key = ("clipv", float(clip_value))
        if key not in self._jit_cache:

            def _clip(grads, inv):
                return jax.tree_util.tree_map(lambda g: (g * inv).clip(-clip_value, clip_value), grads)

            self._jit_cache[key] = jax.jit(_clip, donate_argnums=(0,))
        self._grads = self._jit_cache[key](self._grads, jnp.asarray(inv_scale, jnp.float32))

    # ---- the update ------------------------------------------------------------------
    def _update_fn(self):
        import jax

        if "update" not in self._jit_cache:
            use_scaler = self.scaler is not None and self.scaler.enabled
            to_compute = getattr(self.model, "to_compute_memory", lambda p: p)

            param_out = getattr(self.model, "param_compute_sharding", None)
            opt_out = self._opt_compute_sharding or self.opt_state_sharding

            def _update(params, opt_state, grads, inv_scale, lr_override):
                # Host-offloaded tiers stream into device memory for the update;
                # the caller writes the results back to pinned host.
                opt_state = self.opt_to_compute_memory(opt_state)
                params = to_compute(params)
                # The accumulation buffer may be in reduce_dtype (fp32 over bf16
                # params); the optimizer state mirrors the params, so bring the
                # grads back to the param dtype for the update arithmetic.
                grads = jax.tree_util.tree_map(lambda g, p: g.astype(p.dtype), grads, params)
                new_params, new_opt_state, finite = apply_update_core(
                    self.tx, params, opt_state, grads, inv_scale, lr_override, use_scaler=use_scaler
                )
                # Pin outputs to the derived shardings — an unconstrained donated
                # jit lets XLA re-layout params after the first step (sharding
                # drift away from the configured wrap policy).
                if param_out is not None:
                    new_params = jax.lax.with_sharding_constraint(new_params, param_out)
                if opt_out is not None:
                    new_opt_state = jax.lax.with_sharding_constraint(new_opt_state, opt_out)
                return new_params, new_opt_state, finite

            # XLA:CPU-only: donating (params, opt_state, grads) into the fused
            # update crashes the host runtime when the operands are sharded
            # across forced host-platform devices (SIGSEGV/SIGABRT inside the
            # aliased executable — the multi-device pipeline tests hit it
            # deterministically). Donation is a memory optimization, not a
            # semantics change, so drop it on CPU; TPU/GPU keep the aliasing.
            donate = () if jax.default_backend() == "cpu" else (0, 1, 2)
            self._jit_cache["update"] = jax.jit(_update, donate_argnums=donate)
        return self._jit_cache["update"]

    def step(self):
        """Apply the update if at a sync boundary; no-op otherwise (reference
        optimizer.py:125-152)."""
        import jax
        import jax.numpy as jnp

        self._reject_mpmd("step()")
        if not self.gradient_state.sync_gradients:
            self.step_was_skipped = True
            return
        if self._grads is None:
            self.step_was_skipped = True
            return
        inv_scale = self._unscale_factor()
        lr = self._lr_override
        if self.offload_opt_state:
            # Chunked path: one small program per param group keeps peak HBM at
            # one group's params+grads+state (see apply_chunked_update); it also
            # places params/state back on their storage tiers itself.
            new_params, finite = self.apply_chunked_update(
                self.model.params, self._grads, inv_scale, lr
            )
        else:
            new_params, new_opt_state, finite = self._update_fn()(
                self.model.params, self.opt_state, self._grads, jnp.asarray(inv_scale, jnp.float32), lr
            )
            if hasattr(self.model, "to_storage_memory"):
                new_params = self.model.to_storage_memory(new_params)
            self.opt_state = self.opt_to_storage_memory(new_opt_state)
        self._grads = None
        self._accum_count = 0
        self._grads_unscaled = False
        if self.scaler is not None and self.scaler.enabled:
            found_inf = not bool(finite)
            self.scaler.update(found_inf)
            self.step_was_skipped = found_inf
            if found_inf:
                logger.warning("Skipping optimizer step: non-finite gradients (loss scale -> %s)", self.scaler.scale)
        else:
            self.step_was_skipped = False
        self.model.params = new_params
        # XLA:CPU-only deadlock guard (no-op on TPU/GPU) — see fence_if_cpu.
        fence_if_cpu(new_params)

    def zero_grad(self, set_to_none: bool = True):
        """Clear accumulated grads; no-op mid-accumulation (reference optimizer.py:112)."""
        if self.gradient_state.sync_gradients:
            self._grads = None
            self._accum_count = 0
            self._grads_unscaled = False

    # ---- scheduler hook --------------------------------------------------------------
    def set_learning_rate(self, lr: float):
        """Override the learning rate for subsequent steps (requires the tx to be built
        with `optax.inject_hyperparams`, else schedules inside the tx govern)."""
        self._reject_mpmd("set_learning_rate()")
        self._lr_override = lr

    @property
    def learning_rate(self):
        if self._lr_override is not None:
            return self._lr_override
        if hasattr(self.opt_state, "hyperparams"):
            lr = self.opt_state.hyperparams.get("learning_rate")
            return None if lr is None else float(np.asarray(lr))
        return None

    # ---- checkpoint view -------------------------------------------------------------
    def state_dict(self):
        self._reject_mpmd("state_dict()")
        opt_state = self.opt_state
        if isinstance(opt_state, DiskOptState):
            # Checkpointing sees an ordinary pytree (one pass over the blob).
            opt_state = opt_state.materialize()
        return {"opt_state": opt_state, "scaler": self.scaler.state_dict() if self.scaler else None}

    def load_state_dict(self, state):
        from .parallel.sharding import place_params

        self._reject_mpmd("load_state_dict()")
        if isinstance(self.opt_state, DiskOptState):
            self.opt_state.load(state["opt_state"])
        else:
            # place_params (not device_put): device_put aliases buffers already placed
            # correctly, and the donated update would delete the caller's arrays through
            # that alias on the next step.
            self.opt_state = place_params(state["opt_state"], self.opt_state_sharding)
        if self.scaler is not None and state.get("scaler") is not None:
            self.scaler.load_state_dict(state["scaler"])

"""Multi-process-aware logging (parity: reference logging.py:22-125).

`get_logger(__name__)` returns a `MultiProcessAdapter` whose log methods accept
`main_process_only=` (default True) and `in_order=` kwargs, so N hosts don't emit N
copies of every line. Level defaults from `ACCELERATE_TPU_LOG_LEVEL`.
"""

from __future__ import annotations

import functools
import logging
import os


class MultiProcessAdapter(logging.LoggerAdapter):
    """LoggerAdapter filtering by process rank (parity: reference logging.py:22).

    `main_process_only=True` logs only on global rank 0; `in_order=True` logs on every
    process, serialized by rank with a barrier between turns (debugging aid; slow).
    """

    @staticmethod
    def _should_log(main_process_only):
        from .state import PartialState

        return not main_process_only or PartialState().is_main_process

    def log(self, level, msg, *args, **kwargs):
        if os.environ.get("ACCELERATE_TPU_DISABLE_LOGGING", "false").lower() == "true":
            return
        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        kwargs.setdefault("stacklevel", 2)

        if self.isEnabledFor(level):
            if self._should_log(main_process_only):
                msg, kwargs = self.process(msg, kwargs)
                self.logger.log(level, msg, *args, **kwargs)
            elif in_order:
                from .state import PartialState

                state = PartialState()
                for i in range(state.num_processes):
                    if i == state.process_index:
                        msg, kwargs = self.process(msg, kwargs)
                        self.logger.log(level, msg, *args, **kwargs)
                    state.wait_for_everyone()

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        """Emit a warning exactly once per unique message (parity: reference logging.py:71)."""
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    """Returns a process-aware logger (parity: reference logging.py:85)."""
    if log_level is None:
        log_level = os.environ.get("ACCELERATE_TPU_LOG_LEVEL", None)
    logger = logging.getLogger(name)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})

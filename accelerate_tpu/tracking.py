"""Experiment trackers (L3; reference tracking.py 1023 LoC, 7 integrations).

Same protocol as the reference: a `GeneralTracker` base whose methods run main-process
only (decorator `on_main_process`, reference tracking.py:67), concrete integrations
gated on import probes, and `filter_trackers` resolving user selections
(reference :971). The always-available backends here are JSONL/CSV (offline-first — TPU
pods often have no egress) and TensorBoard when installed; W&B/MLflow/Comet/Aim/ClearML
are thin optional adapters.
"""

from __future__ import annotations

import csv
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Run a tracker method on the main process only (reference tracking.py:67)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Base tracker protocol (reference tracking.py:91). Subclass with `name`,
    `requires_logging_directory`, `store_init_configuration`, and `log`."""

    main_process_only = True

    def __init__(self, _blank=False):
        pass

    @property
    def name(self) -> str:
        raise NotImplementedError

    @property
    def requires_logging_directory(self) -> bool:
        raise NotImplementedError

    @property
    def tracker(self):
        return None

    def store_init_configuration(self, values: dict):
        pass

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        pass

    def finish(self):
        pass


class JSONTracker(GeneralTracker):
    """Offline-first JSONL tracker: one `{"step": .., **values}` object per line.

    Always available; the default when no tracker backend is installed."""

    name = "json"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, "metrics.jsonl")
        self._config_path = os.path.join(self.dir, "config.json")

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(self._config_path, "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        record = {"step": step, "time": time.time()}
        record.update({k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v) for k, v in values.items()})
        with open(self.path, "a") as f:
            f.write(json.dumps(record, default=str) + "\n")


class CSVTracker(GeneralTracker):
    """CSV tracker (columns grow as new metric keys appear)."""

    name = "csv"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, "metrics.csv")
        self._fieldnames: List[str] = []

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.dir, "config.json"), "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        row = {"step": step}
        row.update({k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v) for k, v in values.items()})
        new_fields = [k for k in row if k not in self._fieldnames]
        if new_fields:
            self._fieldnames += new_fields
            rows = []
            if os.path.exists(self.path):
                with open(self.path) as f:
                    rows = list(csv.DictReader(f))
            with open(self.path, "w", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=self._fieldnames)
                writer.writeheader()
                for r in rows:
                    writer.writerow(r)
                writer.writerow(row)
        else:
            with open(self.path, "a", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=self._fieldnames)
                writer.writerow(row)


class TensorBoardTracker(GeneralTracker):
    """TensorBoard via tensorboardX or torch.utils.tensorboard
    (reference tracking.py:165)."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, metric_dict={}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            if isinstance(v, str):
                self.writer.add_text(k, v, global_step=step)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step)
            else:
                self.writer.add_scalar(k, float(v), global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """Weights & Biases (reference tracking.py:276)."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """MLflow (reference tracking.py:579)."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__()
        import mlflow

        self.run = mlflow.start_run(run_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for name, value in values.items():
            mlflow.log_param(name, value)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        metrics = {k: float(v) for k, v in values.items() if isinstance(v, (int, float)) or hasattr(v, "item")}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """Comet ML (reference tracking.py:399)."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from comet_ml import Experiment

        self.run_name = run_name
        self.writer = Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.log_metric(k, v, step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.log_other(k, v)
            elif isinstance(v, dict):
                self.writer.log_metrics(v, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.end()


class AimTracker(GeneralTracker):
    """Aim (reference tracking.py:480)."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = ".", **kwargs):
        super().__init__()
        from aim import Run

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """ClearML (reference tracking.py:724)."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, **kwargs):
        super().__init__()
        from clearml import Task

        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                if step is None:
                    clearml_logger.report_single_value(name=k, value=v, **kwargs)
                else:
                    title, _, series = k.partition("/")
                    clearml_logger.report_scalar(
                        title=title, series=series or title, value=v, iteration=step, **kwargs
                    )

    @on_main_process
    def finish(self):
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    """DVCLive (reference tracking.py:876)."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, live=None, **kwargs):
        super().__init__()
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "json": JSONTracker,
    "csv": CSVTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}

_AVAILABILITY = {
    "json": lambda: True,
    "csv": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
}


def filter_trackers(log_with, logging_dir: Optional[str] = None) -> list:
    """Resolve user selection to available tracker classes/instances
    (reference tracking.py:971). "all" = every available integration."""
    loggers = []
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    for log_type in log_with:
        if isinstance(log_type, GeneralTracker):
            loggers.append(log_type)
            continue
        log_type = str(log_type)
        if log_type == "all":
            for name, probe in _AVAILABILITY.items():
                if probe():
                    loggers.append(name)
            continue
        if log_type not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"Unknown tracker {log_type!r}; choose from {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not _AVAILABILITY[log_type]():
            logger.warning("Tracker %s requested but its package is not installed; skipping.", log_type)
            continue
        if LOGGER_TYPE_TO_CLASS[log_type].requires_logging_directory and logging_dir is None:
            raise ValueError(f"Tracker {log_type} requires a logging_dir/project_dir")
        loggers.append(log_type)
    return loggers

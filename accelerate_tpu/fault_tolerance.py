"""Fault tolerance: restart supervision + preemption checkpointing.

The reference delegates elasticity to torchrun (`--max_restarts`, rdzv args pass
straight through — commands/launch.py:322-345) and has no preemption handling of its
own (SURVEY §5: "none in-tree"). On TPU pods both must be first-class: Cloud TPU VMs
are preemptible (SIGTERM, then hard kill) and pod launches need a per-host supervisor
with a restart budget.

Two pieces:

  - `Supervisor`: runs the training command as a child, restarts on failure up to
    `max_restarts` (with linear backoff), forwards SIGTERM/SIGINT and gives the child
    `grace_period` seconds to checkpoint before the hard kill. This is what
    `accelerate-tpu launch --max_restarts N` wraps around the user script.

  - `PreemptionHandler`: in-process SIGTERM latch. The training loop (or
    `Accelerator.check_preemption()`) polls it at step boundaries; when set, the
    Accelerator saves full state and exits 143 so the supervisor/scheduler sees a
    clean preemption, and `--resume_from_checkpoint latest` continues after respawn.
"""

from __future__ import annotations

import signal
import subprocess
import threading
import time
from typing import Callable, List, Optional

from .logging import get_logger

logger = get_logger(__name__)

PREEMPTED_EXIT_CODE = 143  # 128 + SIGTERM, the conventional graceful-preemption code


class Supervisor:
    """Restart a child command on failure (the torchrun elastic-agent replacement).

    Exit code 0 and `PREEMPTED_EXIT_CODE` end supervision (success / clean preemption
    handoff); any other exit restarts until the budget is spent.
    """

    def __init__(
        self,
        cmd: List[str],
        env: Optional[dict] = None,
        max_restarts: int = 0,
        grace_period: float = 30.0,
        backoff_seconds: float = 1.0,
        max_backoff_seconds: float = 30.0,
        monitor_interval: float = 0.5,
        crash_loop_threshold: int = 3,
        crash_loop_min_uptime: float = 3.0,
        progress_fn: Optional[Callable[[], object]] = None,
        no_progress_threshold: int = 0,
        tracer=None,
    ):
        self.cmd = cmd
        self.env = env
        # Optional telemetry tracer: each child attempt becomes a
        # `supervisor.attempt` span and the trace context (trace id, the
        # attempt span as parent, the trace dir) is injected into the child's
        # environment — the worker side re-arms via `Tracer.from_env`, so a
        # supervised restart chain stitches into ONE timeline (the same
        # two-sided env protocol as ACCELERATE_TPU_FAULT_PLAN). With no
        # tracer, env handling is byte-identical to before.
        self.tracer = tracer
        self.max_restarts = max_restarts
        self.grace_period = grace_period
        self.backoff_seconds = backoff_seconds
        self.max_backoff_seconds = max_backoff_seconds
        # Cadence of the monitor's timed child.wait() cycles (bounds how late a
        # grace-period expiry can be noticed).
        self.monitor_interval = monitor_interval
        # Crash-loop detection: after `crash_loop_threshold` consecutive
        # crashes with the SAME exit code where the child lived less than
        # `crash_loop_min_uptime` seconds, supervision aborts with a tagged
        # diagnostic instead of grinding through the full backoff schedule —
        # a child that dies instantly with an identical code every time (an
        # import error, a missing checkpoint, a bad flag) will not be healed
        # by restart N+1. 0 disables the detector.
        self.crash_loop_threshold = crash_loop_threshold
        self.crash_loop_min_uptime = crash_loop_min_uptime
        # No-forward-progress detection (the uptime detector's complement): a
        # child can run for seconds, die, restart, and land in exactly the
        # same place — e.g. an async checkpoint that is killed before every
        # publish, so each resume replays the same step (the PR-9 livelock).
        # `progress_fn` returns an opaque progress token (typically the newest
        # published checkpoint step); `no_progress_threshold` consecutive
        # failed attempts with an UNCHANGED token abort supervision with a
        # tagged `crash_loop` diagnostic. 0 disables the detector.
        self.progress_fn = progress_fn
        self.no_progress_threshold = no_progress_threshold
        self.crash_loop_detected = False
        #: Which detector tripped: "fast_identical_exits" | "no_forward_progress".
        self.crash_loop_reason: Optional[str] = None
        self._consecutive_no_progress = 0
        self._last_progress_token: object = None
        self._consecutive_fast_identical = 0
        self._last_exit_code: Optional[int] = None
        self.restart_count = 0
        # Goodput accounting (telemetry.StepTimeline's "restart" cause): wall
        # clock this supervisor spent between a child dying and its respawn.
        self.downtime_s = 0.0
        self._child: Optional[subprocess.Popen] = None
        self._terminating = False
        self._kill_deadline: Optional[float] = None

    def _forward_signal(self, signum, frame):
        """Runs ON TOP of the interrupted `child.wait()` frame, which may hold
        `Popen._waitpid_lock` — so this handler must never call poll()/wait()
        itself (their non-blocking lock acquires would fail until the handler
        returns, stalling the full grace period). It only latches the
        terminating flag, stamps the kill deadline, and forwards the signal;
        `_monitor` enforces the grace period."""
        self._terminating = True
        if self._kill_deadline is None:
            self._kill_deadline = time.monotonic() + self.grace_period
        child = self._child
        if child is not None:
            logger.info("supervisor: forwarding signal %d to pid %d", signum, child.pid)
            try:
                child.send_signal(signum)
            except (ProcessLookupError, OSError):
                pass  # child already gone; _monitor will reap it

    def _monitor(self, child: subprocess.Popen) -> int:
        """Timed `child.wait()` cycles (no CPU busy-poll): each cycle blocks up
        to `monitor_interval`, so a forwarded signal's grace expiry is noticed
        within one interval and a child exit is observed immediately."""
        while True:
            timeout = self.monitor_interval
            if self._kill_deadline is not None:
                timeout = min(timeout, max(self._kill_deadline - time.monotonic(), 0.01))
            try:
                return child.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                if self._kill_deadline is not None and time.monotonic() >= self._kill_deadline:
                    logger.warning("supervisor: grace period expired; killing pid %d", child.pid)
                    child.kill()
                    return child.wait()

    def _next_backoff(self) -> float:
        """Linear backoff capped at `max_backoff_seconds` — a tight crash loop
        with a large restart budget must never sleep unboundedly long."""
        return min(self.backoff_seconds * self.restart_count, self.max_backoff_seconds)

    def _attempt_span(self, attempt: int):
        if self.tracer is None:
            return None
        return self.tracer.start_span(
            "supervisor.attempt", category="supervisor",
            attempt=attempt, restarts=self.restart_count,
        )

    def _child_env(self, span) -> Optional[dict]:
        if self.tracer is None:
            return self.env
        import os as _os

        env = dict(self.env) if self.env is not None else dict(_os.environ)
        return self.tracer.inject_env(env, parent=span)

    def run(self) -> int:
        prev_term = signal.signal(signal.SIGTERM, self._forward_signal)
        prev_int = signal.signal(signal.SIGINT, self._forward_signal)
        attempt = 0
        if self.progress_fn is not None:
            self._last_progress_token = self.progress_fn()
        try:
            while True:
                attempt += 1
                span = self._attempt_span(attempt)
                spawned_at = time.monotonic()
                self._child = subprocess.Popen(self.cmd, env=self._child_env(span))
                code = self._monitor(self._child)
                if span is not None:
                    span.annotate(exit_code=code).end()
                    # The standalone event streams immediately: the crash
                    # boundary the chaos trace_complete invariant anchors on.
                    self.tracer.event(
                        "supervisor.child_exit", category="supervisor",
                        attempt=attempt, exit_code=code,
                    )
                if code == 0 or code == PREEMPTED_EXIT_CODE or self._terminating:
                    return code
                uptime = time.monotonic() - spawned_at
                if self.progress_fn is not None and self.no_progress_threshold > 0:
                    token = self.progress_fn()
                    if token == self._last_progress_token:
                        self._consecutive_no_progress += 1
                    else:
                        self._consecutive_no_progress = 0
                    self._last_progress_token = token
                    if self._consecutive_no_progress >= self.no_progress_threshold:
                        self.crash_loop_detected = True
                        self.crash_loop_reason = "no_forward_progress"
                        logger.error(
                            "supervisor: CRASH LOOP — %d consecutive failed attempts "
                            "with no forward progress (progress token stuck at %r); "
                            "refusing further restarts (%d restart(s) left unused). "
                            "diagnostic=crash_loop",
                            self._consecutive_no_progress,
                            token,
                            max(self.max_restarts - self.restart_count, 0),
                        )
                        return code
                fast = uptime < self.crash_loop_min_uptime
                if fast and code == self._last_exit_code:
                    self._consecutive_fast_identical += 1
                else:
                    self._consecutive_fast_identical = 1 if fast else 0
                self._last_exit_code = code
                if (
                    self.crash_loop_threshold > 0
                    and self._consecutive_fast_identical >= self.crash_loop_threshold
                ):
                    # Downtime already charged for every backoff this loop DID
                    # sleep; aborting here just refuses to burn the rest of the
                    # budget on a deterministic failure.
                    self.crash_loop_detected = True
                    self.crash_loop_reason = "fast_identical_exits"
                    logger.error(
                        "supervisor: CRASH LOOP — %d consecutive crashes with identical "
                        "exit code %d, each alive < %.1fs; refusing further restarts "
                        "(%d restart(s) left unused). diagnostic=crash_loop",
                        self._consecutive_fast_identical,
                        code,
                        self.crash_loop_min_uptime,
                        max(self.max_restarts - self.restart_count, 0),
                    )
                    return code
                if self.restart_count >= self.max_restarts:
                    logger.warning(
                        "supervisor: child failed (exit %d); restart budget (%d) exhausted",
                        code,
                        self.max_restarts,
                    )
                    return code
                self.restart_count += 1
                logger.warning(
                    "supervisor: child failed (exit %d); restart %d/%d",
                    code,
                    self.restart_count,
                    self.max_restarts,
                )
                backoff = self._next_backoff()
                self.downtime_s += backoff
                time.sleep(backoff)
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)


class PreemptionHandler:
    """Latch SIGTERM (and optionally SIGINT) for graceful preemption.

    Installed via `Accelerator.register_preemption_checkpoint()` or standalone:

        handler = PreemptionHandler()
        for batch in dl:
            ...
            if handler.preemption_requested:
                accelerator.save_state(ckpt_dir); sys.exit(PREEMPTED_EXIT_CODE)

    CPython only allows `signal.signal` from the MAIN thread: constructed anywhere
    else (notebook executors, launcher worker threads), the handler degrades to a
    warn + permanently-unset latch (`installed` is False) instead of raising —
    `register_preemption_checkpoint` must never crash the training script it is
    trying to protect.
    """

    def __init__(self, catch_sigint: bool = False, on_preempt: Optional[Callable] = None):
        self._requested = threading.Event()
        self.on_preempt = on_preempt
        self._prev = {}
        self.installed = True
        for sig in [signal.SIGTERM] + ([signal.SIGINT] if catch_sigint else []):
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # signal.signal off the main thread (or an exotic interpreter
                # state). A no-op latch keeps the caller alive; preemption then
                # falls back to the supervisor's grace-period kill.
                self.installed = False
                self._prev = {}
                logger.warning(
                    "PreemptionHandler constructed off the main thread; SIGTERM latch "
                    "disabled (preemption_requested will stay False). Construct the "
                    "handler — or call register_preemption_checkpoint — from the main "
                    "thread to enable graceful preemption checkpoints."
                )
                break

    def _handle(self, signum, frame):
        logger.warning("preemption signal %d received; will checkpoint at step boundary", signum)
        self._requested.set()
        if self.on_preempt is not None:
            self.on_preempt()

    @property
    def preemption_requested(self) -> bool:
        return self._requested.is_set()

    def reset(self):
        self._requested.clear()

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}

"""Fault tolerance: restart supervision + preemption checkpointing.

The reference delegates elasticity to torchrun (`--max_restarts`, rdzv args pass
straight through — commands/launch.py:322-345) and has no preemption handling of its
own (SURVEY §5: "none in-tree"). On TPU pods both must be first-class: Cloud TPU VMs
are preemptible (SIGTERM, then hard kill) and pod launches need a per-host supervisor
with a restart budget.

Two pieces:

  - `Supervisor`: runs the training command as a child, restarts on failure up to
    `max_restarts` (with linear backoff), forwards SIGTERM/SIGINT and gives the child
    `grace_period` seconds to checkpoint before the hard kill. This is what
    `accelerate-tpu launch --max_restarts N` wraps around the user script.

  - `PreemptionHandler`: in-process SIGTERM latch. The training loop (or
    `Accelerator.check_preemption()`) polls it at step boundaries; when set, the
    Accelerator saves full state and exits 143 so the supervisor/scheduler sees a
    clean preemption, and `--resume_from_checkpoint latest` continues after respawn.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from .logging import get_logger

logger = get_logger(__name__)

PREEMPTED_EXIT_CODE = 143  # 128 + SIGTERM, the conventional graceful-preemption code


class Supervisor:
    """Restart a child command on failure (the torchrun elastic-agent replacement).

    Exit code 0 and `PREEMPTED_EXIT_CODE` end supervision (success / clean preemption
    handoff); any other exit restarts until the budget is spent.
    """

    def __init__(
        self,
        cmd: List[str],
        env: Optional[dict] = None,
        max_restarts: int = 0,
        grace_period: float = 30.0,
        backoff_seconds: float = 1.0,
        monitor_interval: float = 0.5,
    ):
        self.cmd = cmd
        self.env = env
        self.max_restarts = max_restarts
        self.grace_period = grace_period
        self.backoff_seconds = backoff_seconds
        self.monitor_interval = monitor_interval
        self.restart_count = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminating = False

    def _forward_signal(self, signum, frame):
        self._terminating = True
        child = self._child
        if child is not None and child.poll() is None:
            logger.info("supervisor: forwarding signal %d to pid %d", signum, child.pid)
            child.send_signal(signum)
            deadline = time.time() + self.grace_period
            while child.poll() is None and time.time() < deadline:
                time.sleep(self.monitor_interval)
            if child.poll() is None:
                logger.warning("supervisor: grace period expired; killing pid %d", child.pid)
                child.kill()

    def run(self) -> int:
        prev_term = signal.signal(signal.SIGTERM, self._forward_signal)
        prev_int = signal.signal(signal.SIGINT, self._forward_signal)
        try:
            while True:
                self._child = subprocess.Popen(self.cmd, env=self.env)
                while self._child.poll() is None:
                    time.sleep(self.monitor_interval)
                code = self._child.returncode
                if code == 0 or code == PREEMPTED_EXIT_CODE or self._terminating:
                    return code
                if self.restart_count >= self.max_restarts:
                    logger.warning(
                        "supervisor: child failed (exit %d); restart budget (%d) exhausted",
                        code,
                        self.max_restarts,
                    )
                    return code
                self.restart_count += 1
                logger.warning(
                    "supervisor: child failed (exit %d); restart %d/%d",
                    code,
                    self.restart_count,
                    self.max_restarts,
                )
                time.sleep(self.backoff_seconds * self.restart_count)
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)


class PreemptionHandler:
    """Latch SIGTERM (and optionally SIGINT) for graceful preemption.

    Installed via `Accelerator.register_preemption_checkpoint()` or standalone:

        handler = PreemptionHandler()
        for batch in dl:
            ...
            if handler.preemption_requested:
                accelerator.save_state(ckpt_dir); sys.exit(PREEMPTED_EXIT_CODE)
    """

    def __init__(self, catch_sigint: bool = False, on_preempt: Optional[Callable] = None):
        self._requested = threading.Event()
        self.on_preempt = on_preempt
        self._prev = {}
        for sig in [signal.SIGTERM] + ([signal.SIGINT] if catch_sigint else []):
            self._prev[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame):
        logger.warning("preemption signal %d received; will checkpoint at step boundary", signum)
        self._requested.set()
        if self.on_preempt is not None:
            self.on_preempt()

    @property
    def preemption_requested(self) -> bool:
        return self._requested.is_set()

    def reset(self):
        self._requested.clear()

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}

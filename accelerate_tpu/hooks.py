"""Hook engine (parity: reference hooks.py — ModelHook :33, SequentialHook :91,
add_hook_to_module :120, CpuOffload/UserCpuOffloadHook :661-709).

The reference intercepts `module.forward` by monkey-patching bound methods. Functional
redesign: hooks wrap a Model/PreparedModel's `apply_fn`. A hook sees the full call —
`pre_forward(model, params, args, kwargs)` may move/replace params (that's how offload
hooks stream weights in), `post_forward(model, output)` may transform the output. The
big-model machinery (big_modeling.py) uses explicit layer streaming instead of hooks
for its own execution — this engine is the extension surface users attach custom
behavior with, matching the reference API shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class ModelHook:
    """Base hook (reference hooks.py:33). Subclass and override any stage."""

    no_grad = False

    def init_hook(self, model):
        """Called when attached; may return a modified model."""
        return model

    def pre_forward(self, model, params, args: tuple, kwargs: dict):
        """May replace params/args/kwargs before the wrapped apply."""
        return params, args, kwargs

    def post_forward(self, model, output):
        """May replace the output after the wrapped apply."""
        return output

    def detach_hook(self, model):
        """Called when removed; may return a modified model."""
        return model


class SequentialHook(ModelHook):
    """Runs several hooks in order (reference hooks.py:91)."""

    def __init__(self, *hooks: ModelHook):
        self.hooks = list(hooks)

    def init_hook(self, model):
        for hook in self.hooks:
            model = hook.init_hook(model)
        return model

    def pre_forward(self, model, params, args, kwargs):
        for hook in self.hooks:
            params, args, kwargs = hook.pre_forward(model, params, args, kwargs)
        return params, args, kwargs

    def post_forward(self, model, output):
        for hook in self.hooks:
            output = hook.post_forward(model, output)
        return output

    def detach_hook(self, model):
        for hook in self.hooks:
            model = hook.detach_hook(model)
        return model


def add_hook_to_module(model, hook: ModelHook, append: bool = False):
    """Attach `hook` to a Model/PreparedModel by wrapping its apply_fn
    (reference add_hook_to_module hooks.py:120; `append` chains like :147-153)."""
    if append and getattr(model, "_atl_hook", None) is not None:
        hook = SequentialHook(model._atl_hook, hook)
        remove_hook_from_module(model)

    old_apply = model.apply_fn
    model = hook.init_hook(model)

    def hooked_apply(params, *args, **kwargs):
        params, args, kwargs = hook.pre_forward(model, params, args, kwargs)
        output = old_apply(params, *args, **kwargs)
        return hook.post_forward(model, output)

    model._atl_hook = hook
    model._atl_old_apply = old_apply
    model.apply_fn = hooked_apply
    # PreparedModel caches jitted applies keyed on the old fn; drop them.
    if hasattr(model, "_jit_cache"):
        model._jit_cache.clear()
    return model


def remove_hook_from_module(model, recurse: bool = False):
    """Inverse of add_hook_to_module (reference hooks.py:157)."""
    hook = getattr(model, "_atl_hook", None)
    if hook is not None:
        hook.detach_hook(model)
        model.apply_fn = model._atl_old_apply
        model._atl_hook = None
        model._atl_old_apply = None
        if hasattr(model, "_jit_cache"):
            model._jit_cache.clear()
    return model


class CpuOffload(ModelHook):
    """Keep params on host between calls; move to device for the forward
    (reference CpuOffload hooks.py:661). With `execution_device=None` uses the default
    device. `prev_module_hook` mirrors the pipeline-friendly chaining: attaching model
    B with prev=A's hook offloads A when B runs."""

    def __init__(self, execution_device=None, prev_module_hook: Optional["UserCpuOffloadHook"] = None):
        self.execution_device = execution_device
        self.prev_module_hook = prev_module_hook

    def init_hook(self, model):
        import jax

        # params start on host
        model.params = jax.device_get(model.params)
        return model

    def pre_forward(self, model, params, args, kwargs):
        import jax

        if self.prev_module_hook is not None:
            self.prev_module_hook.offload()
        device = self.execution_device or jax.local_devices()[0]
        params = jax.device_put(params, device)
        return params, args, kwargs


class UserCpuOffloadHook:
    """User handle pairing a model with its CpuOffload hook
    (reference UserCpuOffloadHook hooks.py:682): offload() sends weights home."""

    def __init__(self, model, hook: CpuOffload):
        self.model = model
        self.hook = hook

    def offload(self):
        import jax

        self.model.params = jax.device_get(self.model.params)

    def remove(self):
        remove_hook_from_module(self.model)


def cpu_offload_with_hook(model, execution_device=None, prev_module_hook: Optional[UserCpuOffloadHook] = None):
    """Offload a model to host, returning (model, hook handle) for pipelines
    (reference cpu_offload_with_hook big_modeling.py:275-302)."""
    hook = CpuOffload(execution_device=execution_device, prev_module_hook=prev_module_hook)
    model = add_hook_to_module(model, hook)
    return model, UserCpuOffloadHook(model, hook)


class AlignDevicesHook(ModelHook):
    """Pull params from a weights map onto the execution device before the forward and
    release them after (reference AlignDevicesHook hooks.py:212 — the per-module weight
    streaming primitive; big_modeling's layer streaming is the batched version).

    `weights_map`: Mapping name -> array (e.g. OffloadedWeightsLoader); names follow
    the '/'-joined param-pytree paths.
    """

    def __init__(self, execution_device=None, weights_map=None, offload: bool = True, io_same_device: bool = False):
        self.execution_device = execution_device
        self.weights_map = weights_map
        self.offload = offload
        self.io_same_device = io_same_device

    def pre_forward(self, model, params, args, kwargs):
        import jax

        device = self.execution_device or jax.local_devices()[0]
        if self.weights_map is not None:
            params = _tree_from_flat(
                {name: self.weights_map[name] for name in self.weights_map}
            )
        params = jax.device_put(params, device)
        return params, args, kwargs

    def post_forward(self, model, output):
        if self.offload and self.weights_map is not None:
            # nothing to free explicitly: streamed buffers die with the forward's scope
            pass
        return output


def _tree_from_flat(flat: Dict[str, Any]):
    """'a/b/c' -> nested dicts (inverse of the '/'-joined path flattening)."""
    tree: Dict[str, Any] = {}
    for path, value in flat.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree

"""Process-state core: the three singletons everything else reads.

TPU-native redesign of the reference's state.py:
  - `PartialState` (reference state.py:111) — topology discovery + process control. Instead
    of picking among 8 comm backends and calling `torch.distributed.init_process_group`
    (state.py:183-257), we initialize the JAX coordination service (`jax.distributed`)
    when launched multi-host, and read rank/world topology from the JAX runtime. One
    process drives all local TPU chips (SPMD), so "process" here means *host*, and
    device-level parallelism is expressed through the mesh, not through processes.
  - `AcceleratorState` (reference state.py:808) — mixed precision + the resolved
    parallelism config and the global device `Mesh`. Where the reference re-types itself
    per plugin (DEEPSPEED/FSDP/MEGATRON at state.py:895-913), every plugin here lowers to
    mesh axes + sharding rules, so there is a single code path.
  - `GradientState` (reference state.py:1085) — gradient-accumulation bookkeeping shared
    between Accelerator, dataloaders, optimizers and schedulers. The reference's
    `xm.mark_step` fencing (state.py:1179-1188) has no equivalent: jit boundaries are the
    graph boundaries.

Borg pattern + `_reset_state` hooks mirror the reference so the test-suite singleton
hygiene (reference test_utils/testing.py:427-438) ports directly.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from functools import partial, wraps
from typing import Any, Callable, Optional

import numpy as np

from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    ParallelismConfig,
    PrecisionType,
)
from .utils.environment import parse_flag_from_env

logger = logging.getLogger(__name__)


def is_jax_distributed_initialized() -> bool:
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def _maybe_init_jax_distributed(timeout_seconds: int | None = None):
    """Initialize the JAX coordination service when launched multi-host.

    Replaces MASTER_ADDR/MASTER_PORT + init_process_group (reference state.py:213-257)
    with the coordinator-address protocol. Honors both our env-var protocol
    (ACCELERATE_TPU_*) and JAX's native variables; on Cloud TPU pods
    `jax.distributed.initialize()` can discover everything from metadata, so we also
    initialize when ACCELERATE_TPU_MULTIHOST is set without explicit addresses.
    """
    import jax

    if is_jax_distributed_initialized():
        return
    coord = os.environ.get("ACCELERATE_TPU_COORDINATOR_ADDRESS", os.environ.get("JAX_COORDINATOR_ADDRESS"))
    nproc = os.environ.get("ACCELERATE_TPU_NUM_PROCESSES", os.environ.get("JAX_NUM_PROCESSES"))
    pid = os.environ.get("ACCELERATE_TPU_PROCESS_ID", os.environ.get("JAX_PROCESS_ID"))
    if coord is not None and nproc is not None and pid is not None:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid),
            initialization_timeout=timeout_seconds or 300,
        )
    elif parse_flag_from_env("ACCELERATE_TPU_MULTIHOST"):
        jax.distributed.initialize()


# The reference needs a ThreadLocalSharedDict only for torch_xla TPU v2/v3
# multithreading (state.py:79-107); JAX drives all local cores from a single process, so
# plain class-level dicts are the Borg storage here.
SharedDict = dict


class PartialState:
    """Singleton holding topology + process-control primitives (reference state.py:111).

    Attributes:
        device: the preferred local `jax.Device` for host→device transfers.
        distributed_type: NO | XLA_SPMD | MULTI_HOST.
        num_processes: number of *host* processes (JAX process count).
        process_index / local_process_index: this host's global / node-local rank.
        num_devices / local_device_count: global / per-host accelerator counts.
        debug: when True, collectives verify shapes across processes first
            (reference ACCELERATE_DEBUG_MODE, state.py:172).
    """

    _shared_state = SharedDict()

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        import jax

        self.debug = parse_flag_from_env("ACCELERATE_TPU_DEBUG_MODE")
        timeout = kwargs.pop("timeout", None)
        timeout_seconds = int(timeout.total_seconds()) if timeout is not None else None
        if cpu:
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            jax.config.update("jax_platforms", "cpu")
        _maybe_init_jax_distributed(timeout_seconds)

        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        # With one process per host, the node-local rank equals 0; honor the launcher's
        # env override for setups running several processes on one host.
        self.local_process_index = int(os.environ.get("ACCELERATE_TPU_LOCAL_PROCESS_INDEX", 0))
        self.local_devices = jax.local_devices()
        self.num_devices = jax.device_count()
        self.local_device_count = jax.local_device_count()
        self.device = self.local_devices[0]
        self.platform = self.device.platform

        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif self.num_devices > 1:
            self.distributed_type = DistributedType.XLA_SPMD
        else:
            self.distributed_type = DistributedType.NO
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", 0)

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num processes (hosts): {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local devices: {self.local_device_count} / global devices: {self.num_devices}\n"
            f"Device: {self.device}\n"
        )

    @staticmethod
    def _reset_state():
        """Reset the singleton (test hygiene; reference state.py destroys process groups)."""
        PartialState._shared_state.clear()

    @property
    def initialized(self) -> bool:
        return self._shared_state != {}

    @property
    def use_distributed(self) -> bool:
        return self.num_processes > 1 or self.num_devices > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    def wait_for_everyone(self):
        """Cross-host barrier (reference state.py:348 → torch.distributed.barrier /
        xm.rendezvous). Implemented over the JAX coordination service; a no-op
        single-host since local devices are driven synchronously by one process."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    @contextmanager
    def main_process_first(self):
        """Main process runs the body before the others (reference state.py:484)."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        if not self.is_local_main_process:
            self.wait_for_everyone()
        yield
        if self.is_local_main_process:
            self.wait_for_everyone()

    def on_main_process(self, function: Callable = None):
        """Decorator: run only on the main process (reference state.py:525)."""
        if not self.initialized:
            raise ValueError("The `PartialState` must be initialized before calling this")

        @wraps(function)
        def _inner(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return _inner

    def on_local_main_process(self, function: Callable = None):
        @wraps(function)
        def _inner(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return _inner

    def on_last_process(self, function: Callable):
        @wraps(function)
        def _inner(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return _inner

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return partial(self.on_process, process_index=process_index)

        @wraps(function)
        def _inner(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return _inner

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        if function is None:
            return partial(self.on_local_process, local_process_index=local_process_index)

        @wraps(function)
        def _inner(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return _inner

    def print(self, *args, **kwargs):
        """Print once (main process only) — reference state.py `print`."""
        if self.is_main_process:
            print(*args, **kwargs)

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split `inputs` across host processes, yielding this host's slice
        (reference state.py:393-483; user-facing at accelerator.py:611).

        Accepts list/tuple/dict-of-splittables/np.ndarray/jax.Array. With
        `apply_padding=True` the last element is repeated so every process gets the same
        count (pair with `gather_for_metrics(..)` truncation on the way back).
        """
        if self.num_processes == 1:
            yield inputs
            return

        import jax

        def _split(obj):
            length = len(obj)
            num_samples_per_process, num_extras = divmod(length, self.num_processes)
            start = self.process_index * num_samples_per_process + min(self.process_index, num_extras)
            end = start + num_samples_per_process + (1 if self.process_index < num_extras else 0)
            result = obj[start:end]
            if apply_padding:
                target = num_samples_per_process + (1 if num_extras > 0 else 0)
                while len(result) < target:
                    if isinstance(result, np.ndarray) or isinstance(result, jax.Array):
                        result = np.concatenate([np.asarray(result), np.asarray(result[-1:])], axis=0)
                    else:
                        result = list(result) + list(result[-1:])
            return result

        def _leaf_lengths(obj):
            if isinstance(obj, dict):
                out = []
                for v in obj.values():
                    out.extend(_leaf_lengths(v))
                return out
            return [len(obj)]

        def _split_values(obj):
            # Dicts split recursively (reference state.py:462-465: nested dicts are
            # walked, every non-dict value slices by the same index range).
            if isinstance(obj, dict):
                return {k: _split_values(v) for k, v in obj.items()}
            return _split(obj)

        if isinstance(inputs, dict):
            # Row alignment must hold across the WHOLE tree (a nested value with a
            # different length would silently desynchronize shards).
            if len(set(_leaf_lengths(inputs))) > 1:
                raise ValueError(
                    "All values in a dict passed to `split_between_processes` must be equal length"
                )
        yield _split_values(inputs)

    def destroy_process_group(self):
        """Shut down the coordination service (reference destroys the torch pg)."""
        import jax

        if is_jax_distributed_initialized():
            jax.distributed.shutdown()
        self._reset_state()


class AcceleratorState:
    """Singleton layering precision + mesh + plugins over PartialState
    (reference state.py:808)."""

    _shared_state = SharedDict()

    def __init__(
        self,
        mixed_precision: str = None,
        cpu: bool = False,
        parallelism_config: Optional[ParallelismConfig] = None,
        fsdp_plugin=None,
        deepspeed_plugin=None,
        megatron_lm_plugin=None,
        sequence_parallel_plugin=None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if mixed_precision is not None and mixed_precision != self._mixed_precision:
                raise ValueError(
                    "AcceleratorState already initialized with mixed_precision="
                    f"{self._mixed_precision}; cannot re-init with {mixed_precision}. "
                    "Call AcceleratorState._reset_state() first (tests) or pass the value once."
                )
            return

        self._partial = PartialState(cpu, **kwargs)
        if mixed_precision is None:
            mixed_precision = os.environ.get("ACCELERATE_TPU_MIXED_PRECISION", "no")
        mixed_precision = str(mixed_precision).lower()
        if mixed_precision not in PrecisionType.list():
            raise ValueError(f"mixed_precision must be one of {PrecisionType.list()}, got {mixed_precision}")
        self._mixed_precision = mixed_precision

        # Compatibility shims lower to the two universal primitives (mesh + specs).
        if megatron_lm_plugin is not None and parallelism_config is None:
            parallelism_config = megatron_lm_plugin.to_parallelism_config()
        if deepspeed_plugin is not None and fsdp_plugin is None:
            fsdp_plugin = deepspeed_plugin.to_fsdp_plugin()
        self.parallelism_config = parallelism_config or ParallelismConfig.from_env()
        if sequence_parallel_plugin is not None and self.parallelism_config.seq == 1:
            # Fold the SP degree into the mesh so the "seq" axis is real.
            self.parallelism_config.seq = sequence_parallel_plugin.seq_degree
        self.fsdp_plugin = fsdp_plugin
        self.deepspeed_plugin = deepspeed_plugin
        self.megatron_lm_plugin = megatron_lm_plugin
        self.sequence_parallel_plugin = sequence_parallel_plugin
        self._mesh = None

    # ---- passthroughs to PartialState ------------------------------------------------
    def __getattr__(self, name):
        # Only called when normal lookup fails; delegate topology attrs to PartialState.
        if name in ("_partial", "__dict__"):
            raise AttributeError(name)
        partial_state = self.__dict__.get("_partial")
        if partial_state is not None and hasattr(partial_state, name):
            return getattr(partial_state, name)
        raise AttributeError(f"`AcceleratorState` object has no attribute `{name}`")

    @property
    def initialized(self) -> bool:
        return self._shared_state != {}

    @staticmethod
    def _reset_state(reset_partial_state: bool = False):
        AcceleratorState._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    @property
    def mixed_precision(self) -> str:
        return self._mixed_precision

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        return {"no": jnp.float32, "bf16": jnp.bfloat16, "fp16": jnp.float16, "fp8": jnp.bfloat16}[
            self._mixed_precision
        ]

    @property
    def mesh(self):
        """The global device mesh; built lazily from `parallelism_config`."""
        if self._mesh is None:
            from .parallel.mesh import build_mesh

            self._mesh = build_mesh(self.parallelism_config)
        return self._mesh

    def set_mesh(self, mesh):
        self._mesh = mesh

    @property
    def use_fsdp(self) -> bool:
        return self.fsdp_plugin is not None

    def wait_for_everyone(self):
        self._partial.wait_for_everyone()


class GradientState:
    """Singleton for gradient-accumulation bookkeeping (reference state.py:1085).

    Shared mutable contract between Accelerator ↔ dataloaders ↔ optimizers ↔ schedulers:
      - `sync_gradients`: True on step boundaries (apply update) — set by
        `Accelerator.accumulate` or forced by `end_of_dataloader`.
      - `end_of_dataloader` / `remainder`: set by the active DataLoaderShard so
        `gather_for_metrics` can drop duplicated pad samples (reference
        data_loader.py:377-384 → accelerator.py:2384-2393).
    """

    _shared_state = SharedDict()

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = (
                gradient_accumulation_plugin.to_kwargs() if gradient_accumulation_plugin is not None else {}
            )
            self._is_xla_gradients_synced = False
        if gradient_accumulation_plugin is not None and self.plugin_kwargs != gradient_accumulation_plugin.to_kwargs():
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", False)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def initialized(self) -> bool:
        return GradientState._shared_state != {}

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def __repr__(self):
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation steps: {self.num_steps}\n"
        )

    def _set_sync_gradients(self, sync_gradients: bool):
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader):
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    @staticmethod
    def _reset_state():
        GradientState._shared_state.clear()

"""Setup for accelerate-tpu — a TPU-native training & inference framework on JAX/XLA.

Mirrors the packaging surface of the reference (reference: setup.py:52-70) with a
console entry point for the CLI.
"""

from setuptools import find_packages, setup

setup(
    name="accelerate-tpu",
    version="0.1.0",
    description="TPU-native training and big-model inference framework on JAX/XLA (pjit/GSPMD, shard_map, Pallas)",
    long_description=open("README.md", encoding="utf-8").read(),
    long_description_content_type="text/markdown",
    author="The accelerate-tpu authors",
    license="Apache 2.0",
    packages=find_packages(include=["accelerate_tpu", "accelerate_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax>=0.4.30", "numpy>=1.24", "pyyaml"],
    extras_require={
        "flax": ["flax", "optax"],
        "checkpoint": ["orbax-checkpoint"],
        "test": ["pytest"],
    },
    entry_points={
        "console_scripts": [
            "accelerate-tpu=accelerate_tpu.commands.accelerate_cli:main",
            "accelerate-tpu-launch=accelerate_tpu.commands.launch:main",
        ]
    },
)
